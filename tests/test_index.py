"""Vector-retrieval index subsystem (predictionio_tpu/index).

The contract under test, per ISSUE 12's acceptance criteria:

  - the fused Pallas dot+top-k kernel (interpret mode on CPU) returns
    EXACTLY what the ``ops.topk`` brute-force reference returns —
    identical scores, identical indices modulo exact score ties —
    including ragged tails, tie groups, exclusion masks and item ids
    beyond 2^16;
  - the IVF CPU fallback clears recall@10 >= 0.95 against brute force
    on the fixture (and measures/records that recall at build);
  - a streamed ``POST /model/patch`` item is retrievable WITHOUT a
    ``/reload`` (the ``event_to_servable`` contract extended to
    retrieval), and the index survives a ``/reload`` hot-swap;
  - the streaming recall probe exports ``pio_stream_index_recall`` and
    counts floor breaches;
  - bench/benchcmp treat ``retrieval_qps_recall95`` (higher-better)
    and ``index_build_sec`` (lower-better) direction-aware.
"""

import importlib.util
import json
import os
import pickle

import numpy as np
import pytest

from predictionio_tpu.data.bimap import BiMap
from predictionio_tpu.index import (
    QUERIES_TOTAL,
    SIZE_ITEMS,
    make_index,
    resolve_backend,
)
from predictionio_tpu.index.exact import ExactIndex
from predictionio_tpu.index.ivf import IVFIndex
from predictionio_tpu.index.recall import brute_force_topk, recall_at_k
from predictionio_tpu.models.als import ALSAlgorithm, ALSModel, ALSParams
from predictionio_tpu.ops.als import ALSFactors
from predictionio_tpu.ops.pallas.topk_dot import topk_dot
from predictionio_tpu.ops.topk import NEG_INF, TopKScorer

RNG = np.random.default_rng(42)


def _clustered(n, d, n_clusters=12, seed=5, spread=0.15):
    """Gaussian-mixture vectors — the realistic (clusterable) shape IVF
    is built for; pure iid gaussians are its degenerate worst case."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(n_clusters, d)).astype(np.float32)
    assign = rng.integers(0, n_clusters, size=n)
    return (centers[assign]
            + spread * rng.normal(size=(n, d)).astype(np.float32)
            ).astype(np.float32)


def _brute_masked(vectors, q, k, exclude_rows=None):
    """lax.top_k over the FULL logits matrix — the reference the kernel
    must match. ``exclude_rows``: [B, E] global ids, -1 pads."""
    import jax

    scores = np.atleast_2d(q) @ vectors.T
    if exclude_rows is not None:
        excl = np.atleast_2d(np.asarray(exclude_rows, np.int64))
        for b in range(scores.shape[0]):
            drop = excl[b]
            drop = drop[(drop >= 0) & (drop < vectors.shape[0])]
            scores[b, drop] = float(NEG_INF)
    s, i = jax.lax.top_k(scores, k)
    return np.asarray(s), np.asarray(i)


# ---------------------------------------------------------------------------
# Pallas kernel equivalence (interpret mode)
# ---------------------------------------------------------------------------

class TestTopkDotKernel:
    @pytest.mark.parametrize("I,D,B,k,E", [
        (1024, 16, 4, 8, 1),      # exact tile multiple
        (1300, 16, 4, 8, 4),      # ragged last tile
        (700, 8, 1, 16, 2),       # k bigger than one would guess vs I
        (513, 32, 8, 8, 8),       # one full tile + a 1-row tail
    ])
    def test_matches_brute_force(self, I, D, B, k, E):
        rng = np.random.default_rng(I + D)
        q = rng.normal(size=(B, D)).astype(np.float32)
        items = rng.normal(size=(I, D)).astype(np.float32)
        excl = np.full((B, E), -1, np.int32)
        # valid + out-of-tile + -1 pads
        excl[:, 0] = rng.integers(0, I, size=B)
        s, i = topk_dot(q, items, excl, k, interpret=True)
        bs, bi = _brute_masked(items, q, k, excl)
        np.testing.assert_allclose(np.asarray(s), bs, rtol=1e-5, atol=1e-5)
        assert np.array_equal(np.asarray(i), bi)

    def test_item_ids_beyond_uint16(self):
        """>2^16 items: the winning global id must survive the int32
        iota/merge path (a uint16 anywhere would alias it)."""
        I, D = 66_000, 8
        rng = np.random.default_rng(0)
        items = 0.01 * rng.normal(size=(I, D)).astype(np.float32)
        q = rng.normal(size=(2, D)).astype(np.float32)
        winner = 65_777   # > 2^16, inside the ragged tail region
        items[winner] = 100.0 * q[0] / np.linalg.norm(q[0])
        s, i = topk_dot(q, items, np.full((2, 1), -1, np.int32), 8,
                        interpret=True)
        assert int(np.asarray(i)[0, 0]) == winner

    def test_ties_identical_scores_valid_indices(self):
        """Duplicate item rows tie exactly; the pinned contract is
        identical SCORES and indices drawn from the tied equivalence
        class (lax.top_k's intra-tile order is not promised)."""
        rng = np.random.default_rng(1)
        D = 8
        base = rng.normal(size=(600, D)).astype(np.float32)
        items = np.vstack([base, base[:200]])   # 200 exact-tie pairs
        q = rng.normal(size=(3, D)).astype(np.float32)
        k = 16
        s, i = topk_dot(q, items, np.full((3, 1), -1, np.int32), k,
                        interpret=True)
        bs, _ = _brute_masked(items, q, k)
        np.testing.assert_allclose(np.asarray(s), bs, rtol=1e-5, atol=1e-5)
        # every returned index's true score matches the returned score
        s_np, i_np = np.asarray(s), np.asarray(i)
        for b in range(3):
            true = items[i_np[b]] @ q[b]
            np.testing.assert_allclose(true, s_np[b], rtol=1e-5, atol=1e-5)
            assert len(set(i_np[b].tolist())) == k   # no duplicates

    def test_whole_tile_excluded(self):
        """Excluding every top candidate in one tile forces the merge
        to fill from other tiles — the NEG_INF routing under stress."""
        rng = np.random.default_rng(2)
        items = rng.normal(size=(1024, 8)).astype(np.float32)
        q = rng.normal(size=(1, 8)).astype(np.float32)
        _, top = _brute_masked(items, q, 16)
        excl = top[:, :16].astype(np.int32)       # ban the true top-16
        s, i = topk_dot(q, items, excl, 8, interpret=True)
        bs, bi = _brute_masked(items, q, 8, excl)
        np.testing.assert_allclose(np.asarray(s), bs, rtol=1e-5, atol=1e-5)
        assert np.array_equal(np.asarray(i), bi)


# ---------------------------------------------------------------------------
# ExactIndex
# ---------------------------------------------------------------------------

class TestExactIndex:
    VECS = RNG.normal(size=(900, 12)).astype(np.float32)

    def test_fallback_equals_reference_scorer(self):
        index = make_index(self.VECS, backend="exact")   # auto: XLA on CPU
        assert isinstance(index, ExactIndex)
        assert not index.kernel_plan["engaged"]
        q = RNG.normal(size=(5, 12)).astype(np.float32)
        excl = np.array([3, 7], np.int32)
        s, i = index.search(q, 10, excl)
        rs, ri = TopKScorer(self.VECS).score(q, 10, excl)
        np.testing.assert_array_equal(i, ri)
        np.testing.assert_allclose(s, rs, rtol=1e-6)

    def test_kernel_on_equals_reference(self):
        index = make_index(self.VECS, backend="exact", kernel="on")
        assert index.kernel_plan == {"engaged": True, "reason": "forced on",
                                     "interpret": True}
        q = RNG.normal(size=(3, 12)).astype(np.float32)
        s, i = index.search(q, 10)
        rs, ri = TopKScorer(self.VECS).score(q, 10)
        np.testing.assert_allclose(s, rs, rtol=1e-5, atol=1e-5)
        np.testing.assert_array_equal(i, ri)   # no ties in random data

    @pytest.mark.parametrize("kernel", ["auto", "on"])
    def test_upsert_overwrite_and_append(self, kernel):
        index = make_index(self.VECS.copy(), backend="exact", kernel=kernel)
        q = RNG.normal(size=(12,)).astype(np.float32)
        probe = (q / np.linalg.norm(q)).astype(np.float32)
        # overwrite row 5 to dominate, append a new row that dominates more
        index.upsert(np.array([5]), 50.0 * probe)
        s, i = index.search(probe, 2)
        assert int(i[0, 0]) == 5
        index.upsert(np.array([len(index)]), 99.0 * probe)
        assert len(index) == 901
        s, i = index.search(probe, 2)
        assert int(i[0, 0]) == 900 and int(i[0, 1]) == 5

    def test_empty_index_search(self):
        index = ExactIndex()
        s, i = index.search(np.zeros((2, 4), np.float32), 5)
        assert s.shape == (2, 0) and i.shape == (2, 0)

    def test_k_beyond_catalog_falls_back(self):
        """k above the kernel's bucket eligibility (or the catalog)
        degrades to the XLA fallback, never fails."""
        index = make_index(self.VECS, backend="exact", kernel="on")
        s, i = index.search(RNG.normal(size=(1, 12)).astype(np.float32),
                            5000)
        assert s.shape == (1, 900)
        assert sorted(i[0].tolist()) == list(range(900))


# ---------------------------------------------------------------------------
# IVF
# ---------------------------------------------------------------------------

class TestIVFIndex:
    def test_recall_at_10_clears_floor(self):
        vecs = _clustered(4000, 24)
        index = make_index(vecs, backend="ivf")
        assert isinstance(index, IVFIndex)
        # the build-time autotune already measured >= floor
        assert index.measured_recall >= 0.95
        # independent check with held-out queries
        q = _clustered(48, 24, seed=99)
        assert recall_at_k(index, q, 10) >= 0.95
        stats = index.stats()
        assert stats["nlist"] >= 1 and stats["nprobe"] >= 1
        assert stats["measured_recall"] >= 0.95

    def test_int8_quantization_still_clears_floor(self):
        vecs = _clustered(4000, 24)
        index = make_index(vecs, backend="ivf", quantize="int8")
        assert index.stats()["quantize"] == "int8"
        assert index.measured_recall >= 0.95
        q = _clustered(48, 24, seed=98)
        assert recall_at_k(index, q, 10) >= 0.95

    def test_upsert_new_item_retrievable(self):
        vecs = _clustered(1500, 16)
        index = make_index(vecs, backend="ivf")
        probe = _clustered(1, 16, seed=7)[0]
        probe /= np.linalg.norm(probe)
        index.upsert(np.array([1500]), 30.0 * probe)
        assert len(index) == 1501
        s, i = index.search(probe, 5)
        assert int(i[0, 0]) == 1500
        # overwrite moves the row's list membership too
        index.upsert(np.array([3]), 60.0 * probe)
        s, i = index.search(probe, 5)
        assert int(i[0, 0]) == 3

    def test_exclusions(self):
        vecs = _clustered(800, 16)
        index = make_index(vecs, backend="ivf")
        q = vecs[17]
        _, base = index.search(q, 3)
        top = int(base[0, 0])
        _, excluded = index.search(q, 3, np.array([top], np.int64))
        assert top not in excluded[0].tolist()


# ---------------------------------------------------------------------------
# factory / env selection / metrics
# ---------------------------------------------------------------------------

class TestSelection:
    def test_resolve_backend(self, monkeypatch):
        assert resolve_backend(None) == "exact"
        assert resolve_backend("auto") == "exact"
        assert resolve_backend("ivf") == "ivf"
        monkeypatch.setenv("PIO_INDEX_BACKEND", "ivf")
        assert resolve_backend("exact") == "ivf"   # env beats the arg
        monkeypatch.setenv("PIO_INDEX_BACKEND", "bogus")
        with pytest.raises(ValueError):
            resolve_backend("exact")

    def test_env_selects_ivf_for_models(self, monkeypatch):
        monkeypatch.setenv("PIO_INDEX_BACKEND", "ivf")
        vecs = _clustered(600, 8)
        index = make_index(vecs, backend="auto")
        assert isinstance(index, IVFIndex)

    def test_metrics_exported(self):
        vecs = RNG.normal(size=(50, 8)).astype(np.float32)
        index = make_index(vecs, backend="exact")
        before = QUERIES_TOTAL.labels("exact").value
        index.search(vecs[0], 5)
        assert QUERIES_TOTAL.labels("exact").value == before + 1
        assert SIZE_ITEMS.labels("exact").value == 50.0


# ---------------------------------------------------------------------------
# model wiring (ALSModel container — ALS and two-tower share it)
# ---------------------------------------------------------------------------

def _model(n_users=20, n_items=120, rank=8, seed=11):
    rng = np.random.default_rng(seed)
    model = ALSModel(
        ALSFactors(
            user_factors=rng.normal(size=(n_users, rank)).astype(np.float32),
            item_factors=rng.normal(size=(n_items, rank)).astype(np.float32)),
        BiMap.string_int([f"u{j}" for j in range(n_users)]),
        BiMap.string_int([f"i{j}" for j in range(n_items)]))
    return model


class TestModelWiring:
    def test_recommend_routes_through_index_with_scorer_parity(self):
        model = _model()
        recs = model.recommend("u1", 5, exclude_items=["i3", "i9"])
        assert model._index is not None   # recommend built/used the index
        row = model.user_ids["u1"]
        s, i = TopKScorer(model.item_factors).score(
            model.user_factors[row], 5, np.array([3, 9], np.int32))
        inv = model.item_ids.inverse()
        assert [r[0] for r in recs] == [inv[int(j)] for j in i[0]]

    def test_similar_items_excludes_self(self):
        model = _model()
        sims = model.similar_items("i0", 10)
        names = [n for n, _ in sims]
        assert "i0" not in names and len(names) == 10
        sims2 = model.similar_items("i0", 10, exclude_items=[names[0]])
        assert names[0] not in [n for n, _ in sims2]

    def test_similar_items_self_exclusion_survives_blacklist_overflow(self):
        """A blacklist past the exact backend's max_exclude cap drops
        oldest-first — it must drop ITSELF before the self-exclusion
        (which rides last), and the result filter backstops the query
        item regardless (the code-review finding)."""
        model = _model(n_items=200)
        # make i0 its own best match by a wide margin
        model.item_factors[0] *= 50.0
        blacklist = [f"i{j}" for j in range(100, 180)]   # 80 > cap of 64
        sims = model.similar_items("i0", 10, exclude_items=blacklist)
        assert sims and all(n != "i0" for n, _ in sims)

    def test_predict_item_query(self):
        model = _model()
        algo = ALSAlgorithm(ALSParams(rank=8))
        out = algo.predict(model, {"item": "i4", "num": 3})
        assert len(out["itemScores"]) == 3
        assert all(e["item"] != "i4" for e in out["itemScores"])
        # user queries keep their shape
        out_u = algo.predict(model, {"user": "u2", "num": 3})
        assert len(out_u["itemScores"]) == 3

    def test_patch_upserts_into_live_index_without_rebuild(self):
        model = _model()
        model.retrieval_index()
        index_obj = model._index
        q = np.asarray(model.item_factors[4], np.float32)
        newvec = 40.0 * q / np.linalg.norm(q)
        model.upsert_rows(item_rows=[("brand_new", newvec)])
        assert model._index is index_obj          # upsert, not rebuild
        assert len(index_obj) == 121
        sims = model.similar_items("i4", 3)
        assert sims[0][0] == "brand_new"

    def test_pickle_drops_index_and_rebuilds(self):
        model = _model()
        model.retrieval_index()
        clone = pickle.loads(pickle.dumps(model))
        assert clone._index is None
        assert clone.index_backend == "auto"
        assert [n for n, _ in clone.similar_items("i0", 3)] \
            == [n for n, _ in model.similar_items("i0", 3)]

    def test_warmup_builds_index(self):
        from predictionio_tpu.parallel.mesh import MeshContext

        model = _model()
        ALSAlgorithm(ALSParams(rank=8)).warmup(model, MeshContext())
        assert model._index is not None
        assert model.retrieval_stats()["backend"] == "exact"


# ---------------------------------------------------------------------------
# serving end-to-end: patch -> retrievable without /reload; /reload survival
# ---------------------------------------------------------------------------

@pytest.fixture()
def served_world(tmp_path):
    from predictionio_tpu.data.storage import set_storage
    from predictionio_tpu.serving.engine_server import EngineServer

    from tests.test_stream import _seed_world, _train_reco
    from tests.test_storage import make_storage

    storage = make_storage("eventlog", tmp_path)
    set_storage(storage)
    app = storage.apps().insert("stream")
    storage.events().init(app.id)
    _seed_world(storage, app.id, n_users=30, n_items=20, n_events=600)
    engine, instance = _train_reco(storage, engine_id="idx_e2e",
                                   iterations=6)
    server = EngineServer(engine, "idx_e2e", host="127.0.0.1", port=0,
                          storage=storage, micro_batch=False).start()
    try:
        yield storage, engine, server
    finally:
        server.stop()
        set_storage(None)


class TestServingEndToEnd:
    def _query(self, server, payload):
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/queries.json",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            return json.loads(resp.read())

    def test_patched_item_retrievable_without_reload(self, served_world):
        storage, engine, server = served_world
        model = server.deployment.models[0]
        # warm-up (run by the server at load) built the index
        assert server.status()["retrieval"][0] is not None
        base = self._query(server, {"item": "i3", "num": 5})
        assert base["itemScores"]
        # streamed patch: a brand-new item whose factor shadows i3's
        vec = np.asarray(model.item_factors[model.item_ids["i3"]])
        vec = (1.0001 * vec).tolist()
        server.apply_patch({
            "instanceId": server.deployment.instance.id,
            "algorithms": [{"index": 0, "itemRows":
                            [["patched_item", vec]]}],
        })
        after = self._query(server, {"item": "i3", "num": 5})
        names = [e["item"] for e in after["itemScores"]]
        assert names[0] == "patched_item"   # retrieval, no /reload
        # user -> top-k retrieval sees the full (grown) catalog too
        user_q = self._query(server, {"user": "u1", "num": 21})
        assert len(user_q["itemScores"]) == 21   # 20 trained + patched

    def test_index_survives_reload_hot_swap(self, served_world):
        storage, engine, server = served_world
        server.apply_patch({
            "instanceId": server.deployment.instance.id,
            "algorithms": [{"index": 0, "itemRows":
                            [["ephemeral", [0.0] * 8]]}],
        })
        server.reload()
        status = server.status()
        # the swapped-in deployment rebuilt its own index at warm-up...
        assert status["retrieval"][0] is not None
        answer = self._query(server, {"item": "i3", "num": 5})
        names = [e["item"] for e in answer["itemScores"]]
        # ...from the TRAINED factors: the unreloadable patch row is
        # gone (full retrains own reconciliation — the cursor contract)
        assert "ephemeral" not in names and names


# ---------------------------------------------------------------------------
# streaming recall probe
# ---------------------------------------------------------------------------

class TestStreamRecallProbe:
    def test_probe_exports_gauge_and_counts_breaches(self, tmp_path,
                                                     monkeypatch):
        import datetime as dt

        from predictionio_tpu.data.event import Event
        from predictionio_tpu.data.storage import set_storage
        from predictionio_tpu.obs import metrics as obs_metrics
        from predictionio_tpu.workflow.stream import StreamUpdater

        from tests.test_stream import _seed_world, _train_reco
        from tests.test_storage import make_storage

        monkeypatch.setenv("PIO_STREAM_RECALL_EVERY", "1")
        storage = make_storage("eventlog", tmp_path)
        set_storage(storage)
        try:
            app = storage.apps().insert("stream")
            storage.events().init(app.id)
            _seed_world(storage, app.id, n_users=30, n_items=20,
                        n_events=600)
            engine, instance = _train_reco(storage, engine_id="idx_probe",
                                           iterations=6)
            updater = StreamUpdater(engine, "idx_probe", storage=storage,
                                    instance=instance)
            storage.events().insert_batch(
                [Event(event="rate", entity_type="user", entity_id="u1",
                       target_entity_type="item", target_entity_id="i1",
                       properties={"rating": 4.5},
                       event_time=dt.datetime.now(tz=dt.timezone.utc))],
                app.id)
            stats = updater.poll_once()
            assert stats["published"]
            # the probe ran (EVERY=1): a healthy patched index reads ~1
            assert stats["index_recall"] >= 0.99
            gauge = obs_metrics.REGISTRY.get("pio_stream_index_recall")
            assert gauge.value >= 0.99

            # corrupt the patched index directly (bypassing the model)
            # -> drift becomes visible and the breach counter moves
            model = updater._folders[0].model
            index = model.retrieval_index()
            rng = np.random.default_rng(0)
            index.upsert(
                np.arange(len(index)),
                rng.normal(size=(len(index),
                                 model.item_factors.shape[1])
                           ).astype(np.float32))
            breaches = obs_metrics.REGISTRY.get(
                "pio_stream_recall_breaches_total")
            before = breaches.value
            recall = updater.probe_recall()
            assert recall < 0.95
            assert breaches.value == before + 1
        finally:
            set_storage(None)


# ---------------------------------------------------------------------------
# bench / benchcmp gates
# ---------------------------------------------------------------------------

class TestBenchGates:
    def test_benchcmp_directions(self):
        from predictionio_tpu.tools import benchcmp

        assert benchcmp.lower_is_better("key.index_build_sec")
        assert not benchcmp.lower_is_better("key.retrieval_qps_recall95")
        assert not benchcmp.lower_is_better("key.stream_index_recall")

    def test_benchcmp_gates_retrieval_regression(self, tmp_path):
        from predictionio_tpu.tools import benchcmp

        def round_file(name, qps, build):
            doc = {"parsed": {
                "metric": "m", "value": 1.0,
                "key": {"retrieval_qps_recall95": qps,
                        "index_build_sec": build}}}
            path = tmp_path / name
            path.write_text(json.dumps(doc))
            return str(path)

        files = [round_file("BENCH_r01.json", 1000.0, 2.0),
                 round_file("BENCH_r02.json", 500.0, 2.0)]   # qps halved
        import io

        out = io.StringIO()
        assert benchcmp.run(files, tolerance_pct=10.0, out=out) == 1
        assert "retrieval_qps_recall95" in out.getvalue()
        # build time doubling is a regression too (lower-better)
        files = [round_file("BENCH_r03.json", 1000.0, 2.0),
                 round_file("BENCH_r04.json", 1000.0, 5.0)]
        assert benchcmp.run(files, tolerance_pct=10.0,
                            out=io.StringIO()) == 1

    def test_emit_headline_carries_retrieval_keys(self, tmp_path):
        spec = importlib.util.spec_from_file_location(
            "bench_mod", os.path.join(os.path.dirname(__file__), "..",
                                      "bench.py"))
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        detail = {
            "rmse_gate_passed": True, "rmse_band_passed": True,
            "serve_gate_passed": True, "serve_32_gate_passed": True,
            "row_lane_gate_passed": True, "updates_per_sec": 1.0,
            "retrieval_qps_recall95": 1234.5, "index_build_sec": 0.7,
        }
        line = bench.emit_headline(
            detail, detail_path=str(tmp_path / "d.json"))
        assert line["key"]["retrieval_qps_recall95"] == 1234.5
        assert line["key"]["index_build_sec"] == 0.7
