"""Zero-copy data path: the native columnar->binned builders must be
BIT-IDENTICAL to the Python reference binning
(compress_side(build_segmented_groups(...))), and the chunked H2D
pipeline must place exactly the bytes a single-shot device_put would.

Covers the ISSUE-pinned fixtures: tombstones, compacted logs, empty
groups, >idx16 vocab sizes, ragged-shape fuzz, chunked-pipeline
equivalence, and the mmap'd warm load surviving a concurrent prune.
"""

from __future__ import annotations

import datetime as dt
import os

import numpy as np
import pytest

from predictionio_tpu.data.backends.eventlog import EventLogEventStore
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import EventColumns
from predictionio_tpu.ops import ragged
from predictionio_tpu.ops.als import ALSConfig, ALSTrainer, compress_side

pytestmark = pytest.mark.skipif(
    not __import__("predictionio_tpu.native",
                   fromlist=["native_available"]).native_available("eventlog"),
    reason="C++ toolchain unavailable",
)

UTC = dt.timezone.utc


def _store(tmp_path) -> EventLogEventStore:
    st = EventLogEventStore(str(tmp_path / "events"))
    st.init(1)
    return st


def _fill(st, n=60_000, users=800, items=300, seed=0, buy_frac=0.2):
    rng = np.random.default_rng(seed)
    names = np.where(rng.random(n) < buy_frac, 1, 0).astype(np.int32)
    vals = (0.5 + 0.5 * rng.integers(0, 10, n)).astype(np.float64)
    vals[names == 1] = np.nan  # buy rows carry no rating property
    cols = EventColumns(
        entity_codes=rng.integers(0, users, n).astype(np.int32),
        target_codes=rng.integers(0, items, n).astype(np.int32),
        name_codes=names,
        values=vals,
        times_us=np.arange(n, dtype=np.int64) * 1000,
        entity_vocab=[f"u{i}" for i in range(users)],
        target_vocab=[f"i{i}" for i in range(items)],
        names=["rate", "buy"],
    )
    st.insert_columnar(cols, 1, entity_type="user",
                       target_entity_type="item", value_property="rating")


def _reference(st, skip_mod=0, skip_rem=0, buy_rating=4.0, **knobs):
    """The Python reference pipeline the native builder must match:
    columnar scan -> target-drop -> value resolution -> holdout ->
    build_segmented_groups -> compress_side, per side."""
    cs = st.find_columnar(1, value_property="rating", time_ordered=False,
                          entity_type="user", event_names=["rate", "buy"],
                          target_entity_type="item")
    keep = cs.target_codes >= 0
    u = cs.entity_codes[keep].astype(np.int64)
    i = cs.target_codes[keep].astype(np.int64)
    v = np.nan_to_num(cs.values[keep], nan=0.0).astype(np.float32)
    if "buy" in cs.names:
        buy = cs.names.index("buy")
        v = np.where(cs.name_codes[keep] == buy, np.float32(buy_rating), v)
    hold = (np.arange(len(u)) % skip_mod == skip_rem) if skip_mod else (
        np.zeros(len(u), bool))
    tr = (u[~hold], i[~hold], v[~hold])
    ho = (u[hold], i[hold], v[hold])
    user_sg = ragged.build_segmented_groups(
        tr[0], tr[1], tr[2], len(cs.entity_vocab), **knobs)
    item_sg = ragged.build_segmented_groups(
        tr[1], tr[0], tr[2], len(cs.target_vocab), **knobs)
    return (cs, tr, ho,
            compress_side(user_sg, 0), compress_side(item_sg, 0))


def _assert_side_equal(ref, got):
    np.testing.assert_array_equal(ref.idx_lo, got.idx_lo)
    assert (ref.idx_hi is None) == (got.idx_hi is None)
    if ref.idx_hi is not None:
        np.testing.assert_array_equal(ref.idx_hi, got.idx_hi)
    assert ref.affine == got.affine
    np.testing.assert_array_equal(np.asarray(ref.val), np.asarray(got.val))
    assert (ref.mask is None) == (got.mask is None)
    if ref.mask is not None:
        np.testing.assert_array_equal(ref.mask, got.mask)
    np.testing.assert_array_equal(ref.seg, got.seg)
    np.testing.assert_array_equal(ref.counts, got.counts)
    assert (ref.row_block, ref.group_block, ref.groups_per_shard,
            ref.n_shards) == (got.row_block, got.group_block,
                              got.groups_per_shard, got.n_shards)


def _bin(st, **kw):
    kw.setdefault("value_property", "rating")
    kw.setdefault("overrides", {"buy": 4.0})
    kw.setdefault("entity_type", "user")
    kw.setdefault("event_names", ["rate", "buy"])
    kw.setdefault("target_entity_type", "item")
    return st.bin_columnar(1, **kw)


# -- el_bin_columnar equivalence ------------------------------------------------

def test_el_bin_columnar_matches_python_reference(tmp_path):
    st = _store(tmp_path)
    try:
        _fill(st)
        cs, tr, ho, ref_u, ref_i = _reference(st, skip_mod=20, block_size=512)
        out = _bin(st, skip_mod=20, skip_rem=0, block_size=512)
        assert out.n_rows == len(tr[0])
        assert out.entity_vocab == cs.entity_vocab
        assert out.target_vocab == cs.target_vocab
        _assert_side_equal(ref_u, out.user_side)
        _assert_side_equal(ref_i, out.item_side)
        np.testing.assert_array_equal(ho[0], out.holdout[0].astype(np.int64))
        np.testing.assert_array_equal(ho[1], out.holdout[1].astype(np.int64))
        np.testing.assert_array_equal(ho[2], out.holdout[2])
        # kept-value sum backs the bench's global-mean baseline
        assert out.user_side.kept_value_sum == pytest.approx(
            float(np.sum(tr[2], dtype=np.float64)), rel=1e-9)
    finally:
        st.close()


def test_el_bin_columnar_tombstones_and_compaction(tmp_path):
    st = _store(tmp_path)
    try:
        _fill(st, n=30_000, seed=3)
        # tombstone a slice of rows via the row lane (mixed ids)
        ids = st.insert_batch([
            Event(event="rate", entity_type="user", entity_id=f"u{k % 50}",
                  target_entity_type="item", target_entity_id=f"i{k % 30}",
                  properties={"rating": 2.5},
                  event_time=dt.datetime(2026, 3, 1, tzinfo=UTC))
            for k in range(500)
        ], 1)
        for eid in ids[::3]:
            assert st.delete(eid, 1)
        _, _, _, ref_u, ref_i = _reference(st, block_size=256)
        out = _bin(st, block_size=256)
        _assert_side_equal(ref_u, out.user_side)
        _assert_side_equal(ref_i, out.item_side)
        # compaction renumbers nothing visible: live rows keep order
        st.compact(1)
        _, _, _, ref_u2, ref_i2 = _reference(st, block_size=256)
        out2 = _bin(st, block_size=256)
        _assert_side_equal(ref_u2, out2.user_side)
        _assert_side_equal(ref_i2, out2.item_side)
    finally:
        st.close()


def test_el_bin_columnar_empty_groups_and_single_events(tmp_path):
    """A user whose only event lands in the holdout leaves an EMPTY
    group (vocab row with zero kept entries) — counts 0, factors-solve
    pads; the native plan must match the reference's."""
    st = _store(tmp_path)
    try:
        # user u_only's single event is kept-ordinal 0 -> held out
        evs = [Event(event="rate", entity_type="user", entity_id="u_only",
                     target_entity_type="item", target_entity_id="i0",
                     properties={"rating": 5.0},
                     event_time=dt.datetime(2026, 1, 1, tzinfo=UTC))]
        evs += [Event(event="rate", entity_type="user",
                      entity_id=f"u{k % 37}", target_entity_type="item",
                      target_entity_id=f"i{k % 11}",
                      properties={"rating": (k % 9) / 2.0 + 0.5},
                      event_time=dt.datetime(2026, 1, 2, tzinfo=UTC))
                for k in range(4000)]
        st.insert_batch(evs, 1)
        _, tr, _, ref_u, ref_i = _reference(st, skip_mod=20, block_size=64)
        out = _bin(st, skip_mod=20, skip_rem=0, block_size=64)
        assert out.entity_vocab[0] == "u_only"
        assert out.user_side.counts[0] == 0  # all its events held out
        _assert_side_equal(ref_u, out.user_side)
        _assert_side_equal(ref_i, out.item_side)
    finally:
        st.close()


@pytest.mark.slow
def test_el_bin_columnar_idx16_overflow_vocab(tmp_path):
    """A >2^16 opposing vocab must grow the idx_hi stream, identically
    to the reference's _split_idx."""
    st = _store(tmp_path)
    try:
        n_items = 70_000
        n = 90_000
        rng = np.random.default_rng(5)
        # every item code referenced at least once (dense first-seen)
        items = np.concatenate([
            np.arange(n_items, dtype=np.int32),
            rng.integers(0, n_items, n - n_items).astype(np.int32)])
        cols = EventColumns(
            entity_codes=rng.integers(0, 500, n).astype(np.int32),
            target_codes=items,
            name_codes=np.zeros(n, np.int32),
            values=(0.5 + 0.5 * rng.integers(0, 10, n)).astype(np.float64),
            times_us=np.arange(n, dtype=np.int64),
            entity_vocab=[f"u{i}" for i in range(500)],
            target_vocab=[f"i{i}" for i in range(n_items)],
            names=["rate"],
        )
        st.insert_columnar(cols, 1, entity_type="user",
                           target_entity_type="item",
                           value_property="rating")
        _, _, _, ref_u, ref_i = _reference(st, block_size=512)
        out = _bin(st, block_size=512)
        assert out.user_side.idx_hi is not None      # items are >2^16
        assert out.item_side.idx_hi is None          # users are not
        _assert_side_equal(ref_u, out.user_side)
        _assert_side_equal(ref_i, out.item_side)
    finally:
        st.close()


def test_el_bin_columnar_non_affine_values_keep_f32(tmp_path):
    st = _store(tmp_path)
    try:
        n, users, items = 5000, 60, 40
        rng = np.random.default_rng(9)
        cols = EventColumns(
            entity_codes=rng.integers(0, users, n).astype(np.int32),
            target_codes=rng.integers(0, items, n).astype(np.int32),
            name_codes=np.zeros(n, np.int32),
            values=rng.normal(3.0, 1.0, n),   # continuous: not a ladder
            times_us=np.arange(n, dtype=np.int64),
            entity_vocab=[f"u{i}" for i in range(users)],
            target_vocab=[f"i{i}" for i in range(items)],
            names=["rate"],
        )
        st.insert_columnar(cols, 1, entity_type="user",
                           target_entity_type="item",
                           value_property="rating")
        _, _, _, ref_u, ref_i = _reference(st, block_size=64)
        out = _bin(st, block_size=64)
        assert out.user_side.affine is None
        assert out.user_side.mask is not None
        _assert_side_equal(ref_u, out.user_side)
        _assert_side_equal(ref_i, out.item_side)
    finally:
        st.close()


def test_el_bin_columnar_rejects_unknown_filter(tmp_path):
    st = _store(tmp_path)
    try:
        _fill(st, n=1000)
        with pytest.raises(TypeError):
            _bin(st, limit=5)
    finally:
        st.close()


# -- rb_bin_compressed fuzz -----------------------------------------------------

@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("max_len,n_shards", [(None, 1), (64, 1), (None, 4)])
def test_rb_bin_compressed_fuzz(monkeypatch, seed, max_len, n_shards):
    """Ragged-shape fuzz: the COO-level native builder vs the Python
    two-stage reference across group skew, truncation, sharding, and
    both value regimes (affine ladder / continuous)."""
    monkeypatch.setattr(ragged, "_NATIVE_MIN_NNZ", 0)
    rng = np.random.default_rng(seed)
    n = int(rng.integers(5_000, 40_000))
    n_groups = int(rng.integers(50, 3_000))
    n_items = int(rng.integers(20, 2_000))
    g = rng.integers(0, n_groups, n).astype(np.int64)
    i = (rng.zipf(1.3, n) % n_items).astype(np.int64)
    if seed % 2:
        v = (1.0 + 0.5 * rng.integers(0, 9, n)).astype(np.float32)
    else:
        v = rng.normal(size=n).astype(np.float32)
    # leave a tail of groups EMPTY (vocab larger than touched groups)
    g = np.minimum(g, max(1, n_groups - 10))
    bs = int(rng.choice([64, 512, 4096]))
    got = ragged.build_compressed_segmented(
        g, i, v, n_groups, max_len=max_len, n_shards=n_shards,
        block_size=bs)
    assert got is not None
    sg = ragged.build_segmented_groups(
        g, i, v, n_groups, max_len=max_len, n_shards=n_shards,
        block_size=bs)
    ref = compress_side(sg, 0)
    _assert_side_equal(ref, got)
    assert got.kept_entries == int(sg.counts.sum())


def test_rb_bin_compressed_bad_group_raises(monkeypatch):
    monkeypatch.setattr(ragged, "_NATIVE_MIN_NNZ", 0)
    with pytest.raises(ValueError):
        ragged.build_compressed_segmented(
            np.array([0, 99], np.int64), np.zeros(2, np.int64),
            np.ones(2, np.float32), 10)


# -- chunked H2D pipeline -------------------------------------------------------

def test_chunked_device_put_matches_single_shot():
    from predictionio_tpu.ops.als import _chunked_device_put
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    for a in (rng.integers(0, 255, (4096, 64)).astype(np.uint8),
              rng.normal(size=(1000, 33)).astype(np.float32),
              rng.integers(0, 9, 100_000).astype(np.int32)):
        chunked = _chunked_device_put(a, chunk_bytes=32_768)
        np.testing.assert_array_equal(np.asarray(chunked),
                                      np.asarray(jnp.asarray(a)))
    # below-threshold arrays take the single-shot path unchanged
    small = rng.normal(size=(4, 4)).astype(np.float32)
    np.testing.assert_array_equal(
        np.asarray(_chunked_device_put(small, chunk_bytes=1 << 20)), small)


def test_from_sides_trains_identically_to_coo(monkeypatch):
    """The zero-copy construction (prebuilt sides -> from_sides) must
    produce the exact factors of the classic COO construction."""
    from predictionio_tpu.ops.als import build_compressed_side

    monkeypatch.setattr(ragged, "_NATIVE_MIN_NNZ", 0)
    rng = np.random.default_rng(4)
    n, users, items = 40_000, 500, 200
    u = rng.integers(0, users, n)
    i = rng.integers(0, items, n)
    v = (1.0 + 0.5 * rng.integers(0, 9, n)).astype(np.float64)
    cfg = ALSConfig(rank=8, iterations=3, block_size=512,
                    compute_dtype="float32", cg_dtype="float32")
    ref = ALSTrainer((u, i, v), users, items, cfg).run()
    user_side = build_compressed_side(u, i, v, users, cfg, 1, None)
    item_side = build_compressed_side(i, u, v, items, cfg, 1, None)
    got = ALSTrainer.from_sides(user_side, item_side, users, items, n,
                                cfg).run()
    np.testing.assert_allclose(ref.user_factors, got.user_factors,
                               atol=1e-6)
    np.testing.assert_allclose(ref.item_factors, got.item_factors,
                               atol=1e-6)


def test_double_buffer_env_off_still_equivalent(monkeypatch):
    monkeypatch.setenv("PIO_TRANSFER_DOUBLE_BUFFER", "0")
    from predictionio_tpu.ops.als import build_compressed_side

    rng = np.random.default_rng(6)
    n, users, items = 20_000, 200, 100
    u, i = rng.integers(0, users, n), rng.integers(0, items, n)
    v = (1.0 + 0.5 * rng.integers(0, 9, n)).astype(np.float64)
    cfg = ALSConfig(rank=8, iterations=2, block_size=256,
                    compute_dtype="float32", cg_dtype="float32")
    user_side = build_compressed_side(u, i, v, users, cfg, 1, None)
    item_side = build_compressed_side(i, u, v, items, cfg, 1, None)
    t = ALSTrainer.from_sides(user_side, item_side, users, items, n, cfg)
    f1 = t.run()
    f2 = ALSTrainer((u, i, v), users, items, cfg).run()
    np.testing.assert_allclose(f1.user_factors, f2.user_factors, atol=1e-6)


# -- mmap-backed warm loads -----------------------------------------------------

def test_warm_mmap_load_survives_concurrent_prune(tmp_path, monkeypatch):
    """A warm load holds numpy views over the entry file's mmap; a
    prune (this process or another) unlinking the file must not break
    the in-flight training run — POSIX keeps the mapping alive."""
    monkeypatch.setenv("PIO_BIN_CACHE_DIR", str(tmp_path / "bc"))
    from predictionio_tpu.ops import bincache
    from predictionio_tpu.ops.als import SideLayout, build_compressed_side

    rng = np.random.default_rng(8)
    n, users, items = 30_000, 300, 120
    u, i = rng.integers(0, users, n), rng.integers(0, items, n)
    v = (1.0 + 0.5 * rng.integers(0, 9, n)).astype(np.float64)
    cfg = ALSConfig(rank=8, iterations=2, block_size=256,
                    compute_dtype="float32", cg_dtype="float32")
    user_side = build_compressed_side(u, i, v, users, cfg, 1, None)
    item_side = build_compressed_side(i, u, v, items, cfg, 1, None)
    arrays = {**user_side.to_arrays("u_"), **item_side.to_arrays("i_")}
    meta = {"n_users": users, "n_items": items, "n_shards": 1,
            "total_entries": n, **user_side.meta("u_"),
            **item_side.meta("i_")}
    bincache.save("warmkey", arrays, meta)

    loaded = bincache.load("warmkey")
    assert loaded is not None
    arrs, m2 = loaded
    # concurrent prune: the entry vanishes from disk mid-use
    os.remove(os.path.join(bincache.cache_dir(), "warmkey.bin"))
    assert bincache.load("warmkey") is None
    us = SideLayout.from_arrays(arrs, "u_", m2)
    it = SideLayout.from_arrays(arrs, "i_", m2)
    got = ALSTrainer.from_sides(us, it, users, items, n, cfg).run()
    ref = ALSTrainer((u, i, v), users, items, cfg).run()
    np.testing.assert_allclose(ref.user_factors, got.user_factors,
                               atol=1e-6)


def test_bincache_save_is_atomic_and_prune_skips_fresh_temps(
        tmp_path, monkeypatch):
    monkeypatch.setenv("PIO_BIN_CACHE_DIR", str(tmp_path / "bc"))
    monkeypatch.setenv("PIO_BIN_CACHE_KEEP", "2")
    from predictionio_tpu.ops import bincache

    a = {"x": np.arange(100, dtype=np.int32)}
    for k in ("k1", "k2", "k3"):
        bincache.save(k, a, {"k": k})
    names = sorted(os.listdir(bincache.cache_dir()))
    assert len([f for f in names if f.endswith(".bin")]) == 2  # pruned
    # a FRESH temp (another process's save in flight) survives a prune;
    # a stale one is swept
    fresh = os.path.join(bincache.cache_dir(), "inflight.bin.tmp")
    stale = os.path.join(bincache.cache_dir(), "dead.bin.tmp")
    open(fresh, "wb").write(b"x")
    open(stale, "wb").write(b"x")
    old = 4000.0
    os.utime(stale, (old, old))
    bincache._prune(2)
    assert os.path.exists(fresh)
    assert not os.path.exists(stale)
    # a torn entry (truncated write published by force) degrades to None
    path = os.path.join(bincache.cache_dir(), "torn.bin")
    bincache.save("torn", a, {})
    data = open(path, "rb").read()
    open(path, "wb").write(data[: len(data) // 2])
    assert bincache.load("torn") is None


# -- benchcmp gates -------------------------------------------------------------

def test_benchcmp_gates_datapath_keys(tmp_path):
    """key.bin_sec / key.transfer_sec regress UP (lower-better);
    key.warm_transfer_mb_per_sec regresses DOWN."""
    import io
    import json

    from predictionio_tpu.tools import benchcmp

    assert benchcmp.lower_is_better("key.bin_sec")
    assert benchcmp.lower_is_better("key.transfer_sec")
    assert not benchcmp.lower_is_better("key.warm_transfer_mb_per_sec")

    for n, (b, t) in ((1, (5.0, 10.0)), (2, (9.0, 22.0))):
        (tmp_path / f"BENCH_r0{n}.json").write_text(json.dumps(
            {"parsed": {"metric": "m", "value": 1.0,
                        "key": {"bin_sec": b, "transfer_sec": t}}}))
    out = io.StringIO()
    rc = benchcmp.run([str(tmp_path / "BENCH_r01.json"),
                       str(tmp_path / "BENCH_r02.json")],
                      tolerance_pct=10.0, out=out)
    assert rc == 1
    assert "key.bin_sec" in out.getvalue()
    assert "key.transfer_sec" in out.getvalue()


def test_headline_carries_datapath_keys():
    import bench as bench_mod

    detail = {
        "rmse_gate_passed": True, "rmse_band_passed": True,
        "serve_gate_passed": True, "serve_32_gate_passed": True,
        "row_lane_gate_passed": True, "updates_per_sec": 123.0,
        "bin_sec": 2.5, "transfer_sec": 7.0,
        "warm": {"events_to_model_sec": 9.0, "transfer_mb_per_sec": 88.0},
    }
    line = bench_mod.emit_headline(dict(detail), detail_path=os.devnull)
    assert line["key"]["bin_sec"] == 2.5
    assert line["key"]["transfer_sec"] == 7.0
    assert line["key"]["warm_transfer_mb_per_sec"] == 88.0


def test_rb_bin_compressed_nan_values_stay_uncoded(monkeypatch):
    """Review regression: a NaN among the raw values must force the
    f32+mask layout (np.unique keeps the NaN and the ladder check
    fails in the reference) — the old last-value sentinel collided
    with canonical-NaN bits and dropped it from the distinct set,
    silently affine-coding NaN slots to uniq[0]."""
    monkeypatch.setattr(ragged, "_NATIVE_MIN_NNZ", 0)
    g = np.arange(64, dtype=np.int64) % 8
    i = np.arange(64, dtype=np.int64) % 16
    v = np.where(np.arange(64) % 2 == 0, 2.0, 1.0).astype(np.float32)
    v[0] = np.nan
    got = ragged.build_compressed_segmented(g, i, v, 8, block_size=64)
    assert got.affine is None and got.mask is not None
    ref = compress_side(
        ragged.build_segmented_groups(g, i, v, 8, block_size=64), 0)
    _assert_side_equal(ref, got)


def test_twotower_engine_materializes_coo_from_binned_lane(tmp_path):
    """Review regression: the default-on binned lane hands a COO-less
    PreparedRatings to every algorithm sharing RecoDataSource — the
    two-tower trainer (and the hybrid engine) must materialize the COO
    through the columnar fallback instead of crashing on
    ``pd.ratings >= min_rating``."""
    from predictionio_tpu.core.params import EngineParams
    from predictionio_tpu.data.storage import Storage, set_storage
    from predictionio_tpu.models.twotower import TwoTowerParams
    from predictionio_tpu.parallel.mesh import MeshContext
    from predictionio_tpu.templates.recommendation import (
        RecoDataSourceParams,
    )
    from predictionio_tpu.templates.twotower import twotower_engine

    st = Storage.from_env({
        "PIO_STORAGE_SOURCES_EL_TYPE": "eventlog",
        "PIO_STORAGE_SOURCES_EL_PATH": str(tmp_path),
        **{f"PIO_STORAGE_REPOSITORIES_{r}_{k}": v
           for r in ("METADATA", "EVENTDATA", "MODELDATA")
           for k, v in (("NAME", r.lower()), ("SOURCE", "EL"))}})
    set_storage(st)
    try:
        app = st.apps().insert("tt")
        assert app.id == 1  # _fill writes to app 1
        st.events().init(app.id)
        _fill(st.events(), n=4000, users=60, items=30, seed=7)
        engine = twotower_engine()
        ep = EngineParams(
            data_source_params=("", RecoDataSourceParams(app_name="tt")),
            preparator_params=("", None),
            algorithm_params_list=[("twotower", TwoTowerParams(
                dim=8, embed_dim=8, hidden=(8,), epochs=1,
                batch_size=64))],
            serving_params=("", None))
        result = engine.train(MeshContext(), ep)
        model = result.models[0]
        assert len(model.user_ids) > 0 and len(model.item_ids) > 0
    finally:
        st.events().close()
        set_storage(None)


def test_holdout_views_do_not_pin_side_buffers(tmp_path):
    """Review regression: the holdout COO gets its OWN native owner —
    a retained holdout (bench keeps it for the RMSE gates) must not
    keep the multi-hundred-MB side buffers allocated after the trainer
    released them."""
    def owner_of(arr):
        a = arr
        while a is not None and not hasattr(a, "_owner"):
            a = a.base
        return a._owner

    st = _store(tmp_path)
    try:
        _fill(st, n=5000, users=60, items=30)
        out = _bin(st, skip_mod=20, skip_rem=0, block_size=64)
        side_owner = owner_of(out.user_side.idx_lo)
        hold_owner = owner_of(out.holdout[0])
        assert side_owner is not hold_owner
        assert owner_of(out.item_side.seg) is side_owner
    finally:
        st.close()


def test_read_prepared_is_memoized_per_request():
    from predictionio_tpu.templates.recommendation import BinnedReadRequest

    calls = []
    req = BinnedReadRequest(
        app_name="x", channel_name=None, entity_type="user",
        event_names=["rate"], target_entity_type="item",
        value_property="rating", overrides={})
    sentinel = object()
    req._prepared = sentinel  # a prior consumer's materialization
    assert req.read_prepared() is sentinel  # no second scan
    del calls
