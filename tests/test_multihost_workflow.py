"""The PRODUCT workflow across real process boundaries (VERDICT r2 #1).

Two jax.distributed CPU processes drive the actual `pio train` path —
``workflow.train.run_train`` with the recommendation template — against
ONE shared storage server (rest backend), not ops-level calls:

  - each host reads only its entity-hash shard of the events
    (server-side filtered find_columnar; proven from the server's own
    scan counters) and reassembles full training data over the job's
    interconnect (exchange_columns);
  - storage writes are single-writer: process 0 owns the EngineInstance
    row and model blob, the instance id is broadcast, and both
    processes return the same COMPLETED instance;
  - process 1 then DEPLOYS the instance process 0 persisted
    (prepare_deploy from the shared store) and answers a query —
    train-on-A/deploy-on-B through the real workflow.

Reference equivalents: per-executor HBase region scans
(hbase/HBPEvents.scala:48) + driver-only metadata writes
(CoreWorkflow.scala:60-81) + cross-JVM deploy (CreateServer.scala:190).
"""

import datetime as _dt
import json
import os
import socket
import subprocess
import sys
import urllib.request

import numpy as np

from predictionio_tpu.data.event import Event
from predictionio_tpu.serving.storage_server import StorageServer

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
UTC = _dt.timezone.utc

N_USERS = 20
N_ITEMS = 8
EVENTS_PER_USER = 6

_WORKER = """
import jax
jax.config.update("jax_platforms", "cpu")

from predictionio_tpu.parallel import multihost as mh

assert mh.initialize_from_env() is True, "distributed init did not engage"
assert jax.process_count() == 2

from predictionio_tpu.core.params import EngineParams
from predictionio_tpu.models.als import ALSParams
from predictionio_tpu.templates import recommendation as reco_t
from predictionio_tpu.workflow.train import run_train

engine = reco_t.recommendation_engine()
ep = EngineParams(
    data_source_params=(
        "", reco_t.RecoDataSourceParams(app_name="mhapp", columnar=True)),
    algorithm_params_list=[
        ("als", ALSParams(rank=4, num_iterations=2, block_size=8,
                          compute_dtype="float32", cg_dtype="float32")),
    ],
)
inst = run_train(engine, ep, engine_id="mh-reco")
assert inst.status == "COMPLETED"
print("INSTANCE", inst.id)

if mh.process_index() == 1:
    from predictionio_tpu.data.storage import get_storage
    from predictionio_tpu.workflow.deploy import prepare_deploy

    stored = get_storage().engine_instances().get_latest_completed(
        "mh-reco", "0", "default")
    assert stored is not None, "COMPLETED instance not visible on host B"
    assert stored.id == inst.id
    dep = prepare_deploy(engine, stored)
    res = dep.query({"user": "user_1", "num": 3})
    assert res["itemScores"], res
    print("DEPLOY OK", res["itemScores"][0]["item"])

# keep process 0 (the distributed coordinator) alive until the deploy
# on process 1 has finished
mh.barrier("pio_test_done")
print(f"MHWF OK p{mh.process_index()}")
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _seed(storage):
    app = storage.apps().insert("mhapp")
    storage.events().init(app.id)
    rng = np.random.default_rng(7)
    events, m = [], 0
    for u in range(N_USERS):
        for i in rng.choice(N_ITEMS, size=EVENTS_PER_USER, replace=False):
            events.append(Event(
                event="rate",
                entity_type="user",
                entity_id=f"user_{u}",
                target_entity_type="item",
                target_entity_id=f"item_{i}",
                properties={"rating": float(1 + (u * int(i)) % 5)},
                event_time=_dt.datetime(2026, 1, 1, tzinfo=UTC)
                + _dt.timedelta(minutes=m),
            ))
            m += 1
    storage.events().insert_batch(events, app.id)
    return len(events)


def _worker_env(coord_port, pid, ports, replicas=None):
    env = dict(os.environ)
    env.pop("PYTEST_CURRENT_TEST", None)
    env.update({
        "PYTHONPATH": REPO_ROOT,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "PIO_COORDINATOR_ADDRESS": f"127.0.0.1:{coord_port}",
        "PIO_NUM_PROCESSES": "2",
        "PIO_PROCESS_ID": str(pid),
        "PIO_STORAGE_SOURCES_CENTRAL_TYPE": "rest",
        "PIO_STORAGE_SOURCES_CENTRAL_HOSTS": "127.0.0.1",
        "PIO_STORAGE_SOURCES_CENTRAL_PORTS": ",".join(str(p) for p in ports),
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "meta",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "CENTRAL",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "events",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "CENTRAL",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "models",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "CENTRAL",
    })
    if replicas is not None:
        env["PIO_STORAGE_SOURCES_CENTRAL_REPLICAS"] = str(replicas)
    return env


def _run_workers(coord_port, ports, replicas=None):
    procs, outs = [], []
    try:
        for pid in range(2):
            procs.append(subprocess.Popen(
                [sys.executable, "-c", _WORKER], cwd=REPO_ROOT,
                env=_worker_env(coord_port, pid, ports, replicas),
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            ))
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return procs, outs


def test_two_process_train_and_deploy_via_shared_storage(memory_storage):
    n_events = _seed(memory_storage)
    server = StorageServer(storage=memory_storage, host="127.0.0.1",
                           port=0).start()
    try:
        procs, outs = _run_workers(_free_port(), [server.port])
    finally:
        server.stop()

    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out}"
        assert f"MHWF OK p{pid}" in out
    assert "DEPLOY OK" in outs[1]

    # both processes returned the SAME broadcast instance id
    ids = {
        line.split()[1]
        for out in outs for line in out.splitlines()
        if line.startswith("INSTANCE ")
    }
    assert len(ids) == 1, ids

    # single-writer: exactly one EngineInstance row, one model blob
    instances = memory_storage.engine_instances().get_all()
    assert len(instances) == 1 and instances[0].status == "COMPLETED"
    assert memory_storage.models().get(instances[0].id) is not None

    # host-sharded reads, proven by the server's own counters: one
    # sharded scan per host, together covering every row, each ~1/2
    stats = StorageServer.scan_stats(server)
    scans = stats["columnar_scans"]
    assert len(scans) == 2, scans
    by_shard = {s["shard_index"]: s["rows"] for s in scans}
    assert by_shard.keys() == {0, 1}
    assert all(s["shard_count"] == 2 for s in scans)
    assert sum(by_shard.values()) == n_events
    for rows in by_shard.values():
        assert 0.25 * n_events < rows < 0.75 * n_events, by_shard


def test_multihost_train_survives_dead_storage_replica():
    """The capstone composition (extended per VERDICT r3 item 1): 2
    jax.distributed processes run the real train→deploy workflow
    against a 3-server REPLICATED (R=2) storage tier with one event
    replica KILLED before training — reads fail over to surviving
    replicas and the whole product path completes. THEN the METADATA
    HOME (server 0) is killed too: get_latest_completed, the model
    blob fetch and a fresh deploy+query all still answer from the
    surviving metadata replica, while metadata writes fail loudly
    naming the dead endpoint. The reference's analogue is HBase riding
    out a dead region server on HDFS replicas while Elasticsearch
    serves metadata from its replica shards."""
    backends = []
    servers = []
    for _ in range(3):
        from predictionio_tpu.data.storage import Storage

        b = Storage.from_env({
            "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
            "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "meta",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "events",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "models",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
        })
        backends.append(b)
        servers.append(StorageServer(storage=b, host="127.0.0.1",
                                     port=0).start())
    ports = [s.port for s in servers]
    try:
        # seed THROUGH the replicated client: event copies land on each
        # shard's successor pair, metadata/models on servers 0 AND 1
        from tests.test_sharded_storage import _client

        seeder = _client(ports, replicas=2)
        seeder.apps().insert("mhapp")
        import numpy as np

        rng = np.random.default_rng(7)
        events, m = [], 0
        seeder.events().init(1)
        for u in range(N_USERS):
            for i in rng.choice(N_ITEMS, size=EVENTS_PER_USER,
                                replace=False):
                events.append(Event(
                    event="rate", entity_type="user",
                    entity_id=f"user_{u}",
                    target_entity_type="item",
                    target_entity_id=f"item_{i}",
                    properties={"rating": float(1 + (u * int(i)) % 5)},
                    event_time=_dt.datetime(2026, 1, 1, tzinfo=UTC)
                    + _dt.timedelta(minutes=m),
                ))
                m += 1
        seeder.events().insert_batch(events, 1)
        assert backends[1].apps().get_by_name("mhapp") is not None  # meta
        # replicated onto the successor

        servers[2].stop()  # kill a pure event replica before training

        procs, outs = _run_workers(_free_port(), ports, replicas=2)
        for pid, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"process {pid} failed:\n{out}"
            assert f"MHWF OK p{pid}" in out
        assert "DEPLOY OK" in outs[1]
        # single-writer metadata landed on BOTH replicas
        for b in backends[:2]:
            instances = b.engine_instances().get_all()
            assert len(instances) == 1 and instances[0].status == "COMPLETED"
            assert b.models().get(instances[0].id) is not None

        # -- now kill the METADATA HOME ---------------------------------
        servers[0].stop()
        from predictionio_tpu.data.storage import StorageUnavailableError
        from predictionio_tpu.workflow.deploy import prepare_deploy
        from predictionio_tpu.core.params import EngineParams  # noqa: F401
        from predictionio_tpu.templates import recommendation as reco_t

        survivor = _client(ports, replicas=2)
        stored = survivor.engine_instances().get_latest_completed(
            "mh-reco", "0", "default")
        assert stored is not None, "metadata failover read failed"
        assert survivor.models().get(stored.id) is not None
        dep = prepare_deploy(reco_t.recommendation_engine(), stored,
                             storage=survivor)
        res = dep.query({"user": "user_1", "num": 3})
        assert res["itemScores"], res
        # writes fail loudly, naming the dead home
        import pytest as _pytest

        with _pytest.raises(StorageUnavailableError) as ei:
            survivor.apps().insert("postmortem")
        assert f"http://127.0.0.1:{ports[0]}" in str(ei.value)
    finally:
        for s in servers:
            s.stop()
