"""Flight recorder, on-demand profiling and structured diagnostics
(obs/flight.py, obs/profiler.py, obs/logging.py + the serving wiring):
ring-buffer eviction, stage-timing attribution, error-triggered
capture, the /admin endpoints on live in-process servers, slow-request
logging, trace-log rotation, and the per-batch span satellite."""

import io
import json
import logging
import threading
import time
import urllib.error
import urllib.request

import pytest

from predictionio_tpu.core import (
    Algorithm,
    DataSource,
    Engine,
    FirstServing,
    IdentityPreparator,
)
from predictionio_tpu.core.params import EngineParams, Params
from predictionio_tpu.obs import flight, metrics, trace
from predictionio_tpu.obs import logging as obs_logging
from predictionio_tpu.obs.flight import FlightRecorder
from predictionio_tpu.serving.engine_server import EngineServer, MicroBatcher
from predictionio_tpu.workflow.train import run_train


def http(method, url, body=None, headers=None, timeout=15):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read().decode()


# ---------------------------------------------------------------------------
# recorder core
# ---------------------------------------------------------------------------

def test_ring_buffer_eviction_order():
    rec = FlightRecorder(capacity=3)
    for i in range(5):
        key = rec.begin(f"trace{i}", "S", "GET", f"/r{i}")
        rec.finish(key, 200)
    records = rec.records()
    # oldest two evicted; survivors oldest-first
    assert [r["route"] for r in records] == ["/r2", "/r3", "/r4"]
    assert [r["trace"] for r in records] == ["trace2", "trace3", "trace4"]
    # n limits from the newest end; n <= 0 is "none", not Python's
    # [-0:] == everything
    assert [r["route"] for r in rec.records(2)] == ["/r3", "/r4"]
    assert rec.records(0) == [] and rec.records(-5) == []


def test_stage_attribution_and_unattributed_remainder():
    rec = FlightRecorder(capacity=8)
    key = rec.begin("t1", "S", "POST", "/q")
    rec.note_stage("queue", 0.002, trace_id="t1")
    rec.note_stage("dispatch", 0.003, trace_id="t1")
    rec.note_stage("dispatch", 0.001, trace_id="t1")  # accumulates
    time.sleep(0.01)
    record = rec.finish(key, 200)
    stages = record["stages"]
    assert stages["queue"] == pytest.approx(2.0, abs=0.01)
    assert stages["dispatch"] == pytest.approx(4.0, abs=0.01)
    # stages always sum to the total by construction
    assert sum(stages.values()) == pytest.approx(record["duration_ms"],
                                                 abs=0.05)
    assert stages["unattributed"] > 0


def test_oldest_open_record_owns_the_trace():
    # nested servers can serve the same propagated trace id at once:
    # stage notes must attach to the EDGE (oldest) request
    rec = FlightRecorder(capacity=8)
    edge = rec.begin("shared", "Engine", "POST", "/q")
    inner = rec.begin("shared", "Storage", "GET", "/find")
    rec.note_stage("queue", 0.005, trace_id="shared")
    inner_rec = rec.finish(inner, 200)
    edge_rec = rec.finish(edge, 200)
    assert "queue" in edge_rec["stages"]
    assert "queue" not in inner_rec["stages"]


def test_metric_snapshots_ride_along():
    rec = FlightRecorder(capacity=4, snapshot_interval=0.0)
    key = rec.begin("t1", "S", "GET", "/")
    rec.finish(key, 200)
    dump = rec.dump()
    assert dump["metric_snapshots"], "interval-0 recorder must snapshot"
    snap = dump["metric_snapshots"][-1]
    assert snap["ts"] > 0
    # the snapshot is a compact registry summary, json-serializable
    assert "pio_flight_records_total" in snap["metrics"]
    json.dumps(dump)


# ---------------------------------------------------------------------------
# live engine server: /admin/flight + stage timings + error capture
# ---------------------------------------------------------------------------

from dataclasses import dataclass


@dataclass
class OneParams(Params):
    pass


class OneDataSource(DataSource):
    def __init__(self, params):
        super().__init__(params)

    def read_training(self, ctx):
        return 1.0


class MaybeBoomAlgo(Algorithm):
    """predict() raises on {"boom": true} — the induced handler error."""

    def __init__(self, params):
        super().__init__(params)

    def train(self, ctx, pd):
        return pd + 2.0

    def predict(self, model, query):
        if query.get("boom"):
            raise RuntimeError("induced kaboom")
        return {"result": model * query["mult"]}


def _await_sealed(trace_id, timeout=5.0):
    """The flight record seals on the HANDLER thread after the response
    bytes already reached the client (obs/flight.py finish runs in the
    instrument wrapper's finally) — a test reading the ring right after
    its request must wait for the seal, not race it."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for r in flight.RECORDER.records():
            if r["trace"] == trace_id:
                return r
        time.sleep(0.02)
    raise AssertionError(
        f"record for trace {trace_id} never sealed within {timeout}s: "
        f"{[(r.get('route'), r.get('trace')) for r in flight.RECORDER.records()]}")


@pytest.fixture()
def flight_server(memory_storage):
    engine = Engine(OneDataSource, IdentityPreparator,
                    {"algo": MaybeBoomAlgo}, FirstServing)
    ep = EngineParams(
        data_source_params=("", OneParams()),
        preparator_params=("", None),
        algorithm_params_list=[("algo", OneParams())],
        serving_params=("", None),
    )
    run_train(engine, ep, engine_id="flight", storage=memory_storage)
    flight.RECORDER.clear()
    server = EngineServer(engine, "flight", host="127.0.0.1", port=0,
                          storage=memory_storage).start()
    yield server
    server.stop()
    flight.RECORDER.clear()


def test_admin_flight_returns_recorded_requests(flight_server):
    """Acceptance: GET /admin/flight on a live engine server answers the
    last N completed request records with stage timings and the trace
    id each response carried."""
    base = f"http://127.0.0.1:{flight_server.port}"
    trace_ids = []
    for mult in (2, 3, 4):
        status, headers, body = http("POST", f"{base}/queries.json",
                                     {"mult": mult})
        assert status == 200 and json.loads(body) == {"result": 3.0 * mult}
        trace_ids.append(headers[trace.TRACE_HEADER])

    status, _, body = http("GET", f"{base}/admin/flight")
    assert status == 200
    dump = json.loads(body)
    queries = [r for r in dump["records"] if r["route"] == "/queries.json"]
    assert len(queries) == 3
    # records correlate with the trace ids the clients saw, in order
    assert [r["trace"] for r in queries] == trace_ids
    for r in queries:
        assert r["status"] == 200 and r["method"] == "POST"
        stages = r["stages"]
        # the engine query path attributes queue + dispatch (batcher
        # splits), parse + serialize (handler), remainder explicit
        for stage in ("queue", "dispatch", "parse", "serialize",
                      "unattributed"):
            assert stage in stages, (stage, stages)
        assert sum(stages.values()) == pytest.approx(
            r["duration_ms"], abs=0.1)
        # the request's own span tree rode along, same trace id
        names = [s["name"] for s in r["spans"]]
        assert "serve.query" in names and "http.engineserver" in names
        assert {s["trace"] for s in r["spans"]} == {r["trace"]}
    # ?n= limits from the newest end
    status, _, body = http("GET", f"{base}/admin/flight?n=1")
    limited = json.loads(body)["records"]
    assert len([r for r in limited if r["route"] == "/queries.json"]) <= 1


def test_induced_error_lands_in_dump_without_operator_action(
        flight_server, tmp_path, monkeypatch):
    """Acceptance: an induced handler error appears in the flight dump
    (and, with PIO_FLIGHT_DIR set, as an automatic dump file) with no
    operator action."""
    monkeypatch.setenv("PIO_FLIGHT_DIR", str(tmp_path / "dumps"))
    base = f"http://127.0.0.1:{flight_server.port}"
    status, headers, body = http("POST", f"{base}/queries.json",
                                 {"boom": True})
    assert status == 500
    failed_trace = headers[trace.TRACE_HEADER]

    # the record seals (and the error dump writes) on the handler
    # thread AFTER the 500 already reached the client — wait for it
    _await_sealed(failed_trace)
    status, _, body = http("GET", f"{base}/admin/flight")
    assert status == 200
    record = next(r for r in json.loads(body)["records"]
                  if r["trace"] == failed_trace)
    assert record["status"] == 500
    assert "RuntimeError" in record["error"]
    assert "induced kaboom" in record["error"]
    # the slow/error filter keeps it
    status, _, body = http("GET", f"{base}/admin/flight?slow=1")
    assert any(r["trace"] == failed_trace
               for r in json.loads(body)["records"])
    # the automatic on-disk dump was written and parses (the write
    # follows the seal on the handler thread — poll briefly)
    deadline = time.monotonic() + 5.0
    dumps = []
    while not dumps and time.monotonic() < deadline:
        dumps = list((tmp_path / "dumps").glob("flight-*.json"))
        if not dumps:
            time.sleep(0.02)
    assert dumps, "error must trigger an automatic dump file"
    on_disk = json.loads(dumps[0].read_text())
    assert any(r.get("trace") == failed_trace for r in on_disk["records"])


def test_slow_request_flag_stage_sums_and_json_log(flight_server,
                                                   monkeypatch):
    """PIO_SLOW_MS=0 flags everything: the record is marked slow, its
    stages sum to the total, and the pio.slow logger emits a
    JSON-parseable line carrying the same trace id + breakdown."""
    monkeypatch.setenv("PIO_SLOW_MS", "0")
    buf = io.StringIO()
    handler = logging.StreamHandler(buf)
    handler.setFormatter(obs_logging.JSONFormatter())
    slow_logger = logging.getLogger("pio.slow")
    slow_logger.addHandler(handler)
    old_level = slow_logger.level
    slow_logger.setLevel(logging.WARNING)
    try:
        base = f"http://127.0.0.1:{flight_server.port}"
        status, headers, _ = http("POST", f"{base}/queries.json",
                                  {"mult": 7})
        assert status == 200
        trace_id = headers[trace.TRACE_HEADER]
        record = _await_sealed(trace_id)
        # the pio.slow line fires on the handler thread right after
        # the seal — keep our log handler attached until it lands
        deadline = time.monotonic() + 5.0
        while trace_id not in buf.getvalue() and (
                time.monotonic() < deadline):
            time.sleep(0.02)
    finally:
        slow_logger.removeHandler(handler)
        slow_logger.setLevel(old_level)

    assert record["slow"] is True
    assert sum(record["stages"].values()) == pytest.approx(
        record["duration_ms"], abs=0.1)

    lines = [l for l in buf.getvalue().splitlines() if l.strip()]
    payloads = [json.loads(l) for l in lines]  # every line parses
    mine = next(p for p in payloads if p.get("trace") == trace_id)
    assert mine["level"] == "WARNING"
    assert mine["stages"] == record["stages"]
    assert mine["route"] == "/queries.json"


def test_profile_endpoint_is_clean_noop_on_cpu(flight_server):
    """Acceptance: POST /admin/profile answers a clean 501 on the CPU
    backend (tier-1) instead of pretending to profile."""
    base = f"http://127.0.0.1:{flight_server.port}"
    status, _, body = http("POST", f"{base}/admin/profile?seconds=0.01")
    assert status == 501
    payload = json.loads(body)
    assert payload["backend"] == "cpu"
    assert "no-op on CPU" in payload["message"]
    # malformed seconds is a client error, not a 501
    status, _, _ = http("POST", f"{base}/admin/profile?seconds=soon")
    assert status == 400


def test_profile_endpoint_forced_capture_returns_artifact(
        flight_server, tmp_path, monkeypatch):
    """PIO_PROFILE_FORCE=1 drives the FULL capture path on CPU: the
    endpoint must answer an artifact path that exists."""
    monkeypatch.setenv("PIO_PROFILE_FORCE", "1")
    monkeypatch.setenv("PIO_PROFILE_DIR", str(tmp_path / "prof"))
    base = f"http://127.0.0.1:{flight_server.port}"
    # generous client timeout: the first capture in a cold process pays
    # the jax import + backend init (tens of seconds on a loaded box)
    status, _, body = http("POST", f"{base}/admin/profile?seconds=0.05",
                           timeout=180)
    assert status == 200, body
    payload = json.loads(body)
    assert payload["artifact"] == str(tmp_path / "prof")
    import os

    assert os.path.isdir(payload["artifact"])


# ---------------------------------------------------------------------------
# structured logging
# ---------------------------------------------------------------------------

def test_json_log_lines_carry_active_trace_id():
    buf = io.StringIO()
    handler = logging.StreamHandler(buf)
    handler.setFormatter(obs_logging.JSONFormatter())
    logger = logging.getLogger("test.flight.json")
    logger.addHandler(handler)
    logger.setLevel(logging.INFO)
    try:
        token = trace.activate("cafe" * 8)
        try:
            logger.info("inside a request", extra={"pio": {"k": 1}})
        finally:
            trace.deactivate(token)
        logger.info("outside any request")
    finally:
        logger.removeHandler(handler)
    lines = [json.loads(l) for l in buf.getvalue().splitlines()]
    assert lines[0]["trace"] == "cafe" * 8
    assert lines[0]["message"] == "inside a request"
    assert lines[0]["k"] == 1
    assert "trace" not in lines[1]


def test_plain_formatter_appends_trace():
    record = logging.LogRecord("n", logging.INFO, "p", 1, "msg", (), None)
    fmt = obs_logging.PlainTraceFormatter("%(message)s")
    token = trace.activate("feed" * 8)
    try:
        assert fmt.format(record) == f"msg [trace={'feed' * 8}]"
    finally:
        trace.deactivate(token)
    assert fmt.format(record) == "msg"


# ---------------------------------------------------------------------------
# trace-log rotation (satellite)
# ---------------------------------------------------------------------------

def test_trace_log_rotates_by_size(tmp_path, monkeypatch):
    log_path = tmp_path / "spans.jsonl"
    monkeypatch.setenv("PIO_TRACE_LOG", str(log_path))
    monkeypatch.setenv("PIO_TRACE_LOG_MAX_BYTES", "400")
    counter = metrics.REGISTRY.get("pio_trace_log_rotations_total")
    before = counter.value
    token = trace.activate(trace.new_trace_id())
    try:
        for _ in range(20):
            with trace.span("rotate.me", pad="x" * 40):
                pass
    finally:
        trace.deactivate(token)
    assert counter.value > before
    rolled = tmp_path / "spans.jsonl.1"
    assert rolled.exists()
    # both files hold intact JSON lines (rotation never splits a line)
    for path in (log_path, rolled):
        for line in path.read_text().splitlines():
            assert json.loads(line)["name"] == "rotate.me"


# ---------------------------------------------------------------------------
# per-batch span (satellite)
# ---------------------------------------------------------------------------

def test_multi_query_batch_span_carries_member_trace_ids():
    trace.clear_recent()
    release = threading.Event()

    def run_one(payload):
        release.wait(2.0)  # first (lone) dispatch parks the worker
        return payload

    def run_batch(payloads):
        return payloads

    batcher = MicroBatcher(run_batch, run_one, max_batch=16)
    try:
        member_ids = []
        threads = []

        def lone():
            batcher.submit("lone")

        t0 = threading.Thread(target=lone)
        t0.start()
        time.sleep(0.05)  # the worker is now parked inside run_one

        def submit_traced(tid):
            token = trace.activate(tid)
            try:
                assert batcher.submit(f"q-{tid}") == f"q-{tid}"
            finally:
                trace.deactivate(token)

        for i in range(4):
            tid = trace.new_trace_id()
            member_ids.append(tid)
            th = threading.Thread(target=submit_traced, args=(tid,))
            th.start()
            threads.append(th)
        time.sleep(0.05)  # queued behind the parked worker
        release.set()
        t0.join(5)
        for th in threads:
            th.join(5)
    finally:
        batcher.stop()

    batch_spans = [s for s in trace.recent_spans()
                   if s["name"] == "serve.batch"]
    assert batch_spans, "a >1 dispatch must emit its serve.batch span"
    recorded_members = [m for s in batch_spans for m in s["members"]]
    assert set(member_ids) <= set(recorded_members)
    assert all(s["batch_size"] > 1 for s in batch_spans)


# ---------------------------------------------------------------------------
# CLI: pio flight / pio metrics --json
# ---------------------------------------------------------------------------

def test_pio_flight_cli_dumps_live_server(flight_server, capsys):
    from predictionio_tpu.tools.cli import main

    base = f"http://127.0.0.1:{flight_server.port}"
    assert http("POST", f"{base}/queries.json", {"mult": 2})[0] == 200
    assert main(["flight", "--url", base, "-n", "5"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert any(r["route"] == "/queries.json" for r in payload["records"])


def test_pio_metrics_json_is_machine_readable(flight_server, capsys):
    from predictionio_tpu.tools.cli import main

    base = f"http://127.0.0.1:{flight_server.port}"
    assert http("POST", f"{base}/queries.json", {"mult": 2})[0] == 200
    # in-process registry mode
    assert main(["metrics", "--json"]) == 0
    samples = json.loads(capsys.readouterr().out)
    assert samples['pio_serving_request_seconds_count{engine="flight"}'] >= 1
    # server mode produces the same flat shape
    assert main(["metrics", "--json", "--url", base]) == 0
    remote = json.loads(capsys.readouterr().out)
    assert remote['pio_serving_request_seconds_count{engine="flight"}'] >= 1


# ---------------------------------------------------------------------------
# dashboard flight view (satellite)
# ---------------------------------------------------------------------------

def test_dashboard_flight_view(memory_storage):
    from predictionio_tpu.tools.dashboard import DashboardServer

    flight.RECORDER.clear()
    server = DashboardServer(storage=memory_storage, host="127.0.0.1",
                             port=0).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        assert http("GET", f"{base}/")[0] == 200  # recorded by flight
        status, _, html_body = http("GET", f"{base}/flight")
        assert status == 200
        assert "Flight recorder" in html_body
        assert "/admin/flight" in html_body
        status, _, slow_body = http("GET", f"{base}/flight?slow=1")
        assert status == 200 and "Slow / errored" in slow_body
        # the JSON dump route works on the dashboard too
        status, _, body = http("GET", f"{base}/admin/flight")
        assert status == 200
        assert any(r["route"] == "/" for r in json.loads(body)["records"])
    finally:
        server.stop()
        flight.RECORDER.clear()
