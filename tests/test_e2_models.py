"""e2 algorithm library tests.

Mirrors the reference suites (e2/src/test/.../engine/
CategoricalNaiveBayesTest.scala, MarkovChainTest.scala,
evaluation/CrossValidationTest.scala) including their numeric fixtures,
so the JAX implementations are checked against the exact values the
reference asserts.
"""

import math

import numpy as np
import pytest

from predictionio_tpu.core.cross_validation import split_data
from predictionio_tpu.models import markov, naive_bayes
from predictionio_tpu.models.naive_bayes import LabeledPoint

TOL = 1e-4

BANANA, ORANGE, OTHER = "Banana", "Orange", "Other Fruit"
LONG, NOT_LONG = "Long", "Not Long"
SWEET, NOT_SWEET = "Sweet", "Not Sweet"
YELLOW, NOT_YELLOW = "Yellow", "Not Yellow"

FRUIT_POINTS = [
    LabeledPoint(BANANA, [LONG, SWEET, YELLOW]),
    LabeledPoint(BANANA, [LONG, SWEET, YELLOW]),
    LabeledPoint(BANANA, [LONG, SWEET, YELLOW]),
    LabeledPoint(BANANA, [LONG, SWEET, YELLOW]),
    LabeledPoint(BANANA, [NOT_LONG, NOT_SWEET, NOT_YELLOW]),
    LabeledPoint(ORANGE, [NOT_LONG, SWEET, NOT_YELLOW]),
    LabeledPoint(ORANGE, [NOT_LONG, NOT_SWEET, NOT_YELLOW]),
    LabeledPoint(OTHER, [LONG, SWEET, NOT_YELLOW]),
    LabeledPoint(OTHER, [NOT_LONG, SWEET, NOT_YELLOW]),
    LabeledPoint(OTHER, [LONG, SWEET, YELLOW]),
    LabeledPoint(OTHER, [NOT_LONG, NOT_SWEET, NOT_YELLOW]),
]


@pytest.fixture(scope="module")
def fruit_model():
    return naive_bayes.train(FRUIT_POINTS)


class TestCategoricalNaiveBayes:
    # ref: CategoricalNaiveBayesTest.scala:27-69
    def test_priors_and_likelihoods(self, fruit_model):
        m = fruit_model
        assert m.priors[BANANA] == pytest.approx(-0.7885, abs=TOL)
        assert m.priors[ORANGE] == pytest.approx(-1.7047, abs=TOL)
        assert m.priors[OTHER] == pytest.approx(-1.0116, abs=TOL)

        lik = m.likelihoods
        assert lik[BANANA][0][LONG] == pytest.approx(math.log(4 / 5), abs=TOL)
        assert lik[BANANA][0][NOT_LONG] == pytest.approx(math.log(1 / 5), abs=TOL)
        assert lik[BANANA][1][SWEET] == pytest.approx(math.log(4 / 5), abs=TOL)
        assert lik[BANANA][2][YELLOW] == pytest.approx(math.log(4 / 5), abs=TOL)
        # Orange never seen Long / Yellow (ref :48,55)
        assert LONG not in lik[ORANGE][0]
        assert lik[ORANGE][0][NOT_LONG] == pytest.approx(0.0, abs=TOL)
        assert YELLOW not in lik[ORANGE][2]
        assert lik[OTHER][0][LONG] == pytest.approx(math.log(2 / 4), abs=TOL)
        assert lik[OTHER][1][SWEET] == pytest.approx(math.log(3 / 4), abs=TOL)

    # ref: :71-82
    def test_log_score(self, fruit_model):
        score = fruit_model.log_score(
            LabeledPoint(BANANA, [LONG, NOT_SWEET, NOT_YELLOW]))
        assert score is not None
        assert score == pytest.approx(-4.2304, abs=TOL)

    # ref: :84-95
    def test_log_score_unseen_feature_is_neg_inf(self, fruit_model):
        score = fruit_model.log_score(
            LabeledPoint(BANANA, [LONG, NOT_SWEET, "Not Exist"]))
        assert score == float("-inf")

    # ref: :97-106
    def test_log_score_unknown_label_is_none(self, fruit_model):
        score = fruit_model.log_score(
            LabeledPoint("Not Exist", [LONG, NOT_SWEET, YELLOW]))
        assert score is None

    # ref: :109-123
    def test_custom_default_likelihood(self, fruit_model):
        score = fruit_model.log_score(
            LabeledPoint(BANANA, [LONG, NOT_SWEET, "Not Exist"]),
            default_likelihood=lambda ls: min(ls) - math.log(2),
        )
        assert score == pytest.approx(-4.9236, abs=TOL)

    def test_baked_default_matches_callable(self):
        # Baking the default at train time must equal scoring with the
        # same callable at query time.
        fn = lambda ls: (min(ls) - math.log(2)) if ls else float("-inf")
        m = naive_bayes.train(FRUIT_POINTS, default_likelihood=fn)
        baked = m.log_score(LabeledPoint(BANANA, [LONG, NOT_SWEET, "Not Exist"]))
        assert baked == pytest.approx(-4.9236, abs=TOL)

    # ref: :125-130
    def test_predict(self, fruit_model):
        assert fruit_model.predict([LONG, SWEET, YELLOW]) == BANANA

    def test_predict_batch_matches_single(self, fruit_model):
        batch = [
            [LONG, SWEET, YELLOW],
            [NOT_LONG, NOT_SWEET, NOT_YELLOW],
            [NOT_LONG, SWEET, NOT_YELLOW],
        ]
        assert fruit_model.predict_batch(batch) == [
            fruit_model.predict(f) for f in batch
        ]

    def test_score_batch_shape(self, fruit_model):
        scores = fruit_model.score_batch([[LONG, SWEET, YELLOW]] * 3)
        assert scores.shape == (3, 3)

    def test_inconsistent_arity_raises(self, fruit_model):
        with pytest.raises(ValueError):
            fruit_model.encode_features([[LONG, SWEET]])
        with pytest.raises(ValueError):
            naive_bayes.train([
                LabeledPoint("a", ["x"]),
                LabeledPoint("b", ["x", "y"]),
            ])


# ref fixtures: MarkovChainFixture.scala
TWO_BY_TWO = ([0, 0, 1, 1], [0, 1, 0, 1], [3, 7, 10, 10])
FIVE_BY_FIVE = (
    [0, 0, 1, 1, 1, 1, 1, 2, 2, 2, 3, 3, 3, 4, 4, 4],
    [1, 2, 0, 1, 2, 3, 4, 1, 2, 4, 0, 3, 4, 1, 3, 4],
    [12, 8, 3, 3, 9, 2, 8, 10, 8, 10, 2, 3, 4, 7, 8, 10],
)


class TestMarkovChain:
    # ref: MarkovChainTest.scala:13-23
    def test_train_two_by_two(self):
        model = markov.train(TWO_BY_TWO, n_states=2, top_n=2)
        assert model.top_n == 2
        assert model.transition_row(0) == [
            (0, pytest.approx(0.3)), (1, pytest.approx(0.7))]
        assert model.transition_row(1) == [
            (0, pytest.approx(0.5)), (1, pytest.approx(0.5))]

    # ref: :25-40 — keep top-N only, normalized by FULL row total
    def test_top_n_only(self):
        model = markov.train(FIVE_BY_FIVE, n_states=5, top_n=2)
        assert model.transition_row(0) == [
            (1, pytest.approx(0.6)), (2, pytest.approx(0.4))]
        assert model.transition_row(1) == [
            (2, pytest.approx(9 / 25)), (4, pytest.approx(8 / 25))]
        assert model.transition_row(2) == [
            (1, pytest.approx(10 / 28)), (4, pytest.approx(10 / 28))]
        assert model.transition_row(3) == [
            (3, pytest.approx(3 / 9)), (4, pytest.approx(4 / 9))]
        assert model.transition_row(4) == [
            (3, pytest.approx(8 / 25)), (4, pytest.approx(0.4))]

    # ref: :42-50
    def test_predict(self):
        model = markov.train(TWO_BY_TWO, n_states=2, top_n=2)
        next_state = model.predict([0.4, 0.6])
        assert next_state == [pytest.approx(0.42, abs=1e-6),
                              pytest.approx(0.58, abs=1e-6)]

    def test_empty_row(self):
        model = markov.train(([0], [1], [5.0]), n_states=3, top_n=2)
        assert model.transition_row(2) == []
        out = model.predict([0.0, 0.0, 1.0])
        assert out == [0.0, 0.0, 0.0]

    def test_state_length_mismatch(self):
        model = markov.train(TWO_BY_TWO, n_states=2, top_n=2)
        with pytest.raises(ValueError):
            model.predict([1.0, 0.0, 0.0])

    def test_duplicate_entries_are_combined(self):
        # streaming form: one entry per observed transition
        model = markov.train(([0, 0, 0], [1, 1, 2], [3.0, 4.0, 5.0]),
                             n_states=3, top_n=1)
        assert model.transition_row(0) == [(1, pytest.approx(7 / 12))]
        model2 = markov.train(([0, 0, 0], [1, 1, 2], [3.0, 4.0, 5.0]),
                              n_states=3, top_n=2)
        assert model2.transition_row(0) == [
            (1, pytest.approx(7 / 12)), (2, pytest.approx(5 / 12))]

    def test_out_of_range_states_rejected(self):
        with pytest.raises(ValueError):
            markov.train(([0], [5], [1.0]), n_states=2, top_n=1)
        with pytest.raises(ValueError):
            markov.train(([-1], [0], [1.0]), n_states=2, top_n=1)

    def test_no_entries(self):
        model = markov.train(([], [], []), n_states=2, top_n=2)
        assert model.predict([1.0, 0.0]) == [0.0, 0.0]


class TestCrossValidation:
    # ref: CrossValidationTest.scala — idx % k == foldIdx selects test points
    def test_fold_membership(self):
        data = list(range(10))
        folds = split_data(
            3, data, "info",
            training_data_creator=list,
            query_creator=lambda d: ("q", d),
            actual_creator=lambda d: ("a", d),
        )
        assert len(folds) == 3
        for fold_idx, (td, ei, qa) in enumerate(folds):
            assert ei == "info"
            test_pts = [q[1] for q, _ in qa]
            assert test_pts == [d for i, d in enumerate(data) if i % 3 == fold_idx]
            assert td == [d for i, d in enumerate(data) if i % 3 != fold_idx]
            assert sorted(td + test_pts) == data
            assert all(a == ("a", q[1]) for q, a in qa)

    def test_k_one(self):
        folds = split_data(1, [1, 2], None, list, lambda d: d, lambda d: d)
        assert len(folds) == 1
        td, _, qa = folds[0]
        assert td == [] and [q for q, _ in qa] == [1, 2]

    def test_invalid_k(self):
        with pytest.raises(ValueError):
            split_data(0, [1], None, list, lambda d: d, lambda d: d)
