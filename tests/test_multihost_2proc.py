"""Real 2-process jax.distributed exercise (VERDICT r1 item 4).

Two CPU subprocesses (coordinator on localhost, 2 forced local devices
each -> 4 global) run initialize_from_env, assemble a global array from
per-host shards, reconcile counts with all_hosts_sum, and train a small
DP-sharded ALS whose factors must match the single-device oracle — the
degenerate single-process paths tested in test_multihost.py actually
crossing process boundaries here (SURVEY.md §7.9; the reference's
equivalent surface is Spark driver/executor, testable only in local
mode there)."""

import os
import socket
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = """
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from predictionio_tpu.parallel import multihost as mh
from predictionio_tpu.parallel.mesh import create_mesh

assert mh.initialize_from_env() is True, "distributed init did not engage"
assert jax.process_count() == 2
assert jax.device_count() == 4, jax.device_count()
assert jax.local_device_count() == 2

mesh = create_mesh({"data": 4})

# global_array: each host contributes its contiguous axis-0 shard
n = 16
sl = mh.host_shard_slice(n)
full = np.arange(n * 3, dtype=np.float32).reshape(n, 3)
g = mh.global_array(full[sl], mesh, "data")
assert g.shape == (n, 3)
total = jax.jit(
    lambda a: a.sum(), out_shardings=NamedSharding(mesh, P())
)(g)
np.testing.assert_allclose(float(total), full.sum())

# all_hosts_sum: per-host counts reconcile across processes
counts = np.array([10.0 + mh.process_index(), 1.0])
summed = mh.all_hosts_sum(counts, mesh)
np.testing.assert_allclose(summed, [21.0, 2.0])   # (10+0) + (10+1), 1+1

# DP-sharded ALS across the 2-process mesh matches the 1-device oracle
from predictionio_tpu.ops.als import ALSConfig, als_train

rng = np.random.default_rng(3)
nnz, n_users, n_items = 400, 32, 16
coo = (rng.integers(0, n_users, nnz), rng.integers(0, n_items, nnz),
       (rng.random(nnz) * 4 + 1).astype(np.float32))
cfg = ALSConfig(rank=8, iterations=2, reg=0.1, block_size=8, seg_len=8,
                compute_dtype="float32", cg_dtype="float32")
sharded = als_train(coo, n_users, n_items, cfg, mesh=mesh)
oracle = als_train(coo, n_users, n_items, cfg, mesh=None)
np.testing.assert_allclose(
    sharded.user_factors, oracle.user_factors, rtol=2e-3, atol=2e-3
)
print(f"MULTIHOST2 OK p{mh.process_index()}")
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_distributed(tmp_path):
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("PYTEST_CURRENT_TEST", None)
        env.update(
            {
                "PYTHONPATH": REPO_ROOT,
                "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
                "PIO_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
                "PIO_NUM_PROCESSES": "2",
                "PIO_PROCESS_ID": str(pid),
            }
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", _WORKER], cwd=REPO_ROOT, env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            )
        )
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out}"
        assert f"MULTIHOST2 OK p{pid}" in out
