"""Replicated METADATA / MODELDATA (VERDICT r3 item 1) + tier-resolved
`pio status` exit codes (item 9).

The reference's metadata tier survives machine loss because
Elasticsearch replicates every index across its cluster
(elasticsearch/StorageClient.scala:42) and HDFS keeps 3 copies of each
model blob (hdfs/HDFSModels.scala:28). Here `REPLICAS=R` replicates
apps/keys/channels/manifests/instances and model blobs across the
first R storage servers: synchronous all-replica writes (loud failure
naming the dead endpoint), owner-preferring read failover, and
owner-authoritative anti-entropy via `pio storagerepair`.
"""

import dataclasses
import datetime as _dt

import pytest

from predictionio_tpu.data.metadata import (
    AccessKey,
    EngineInstance,
    EngineManifest,
    Model,
)
from predictionio_tpu.data.storage import (
    StorageError,
    StorageUnavailableError,
    set_storage,
)
from predictionio_tpu.serving.storage_server import StorageServer

from tests.test_sharded_storage import _client, _memory_storage

UTC = _dt.timezone.utc


@pytest.fixture()
def three_replicated():
    """Three storage servers, REPLICAS=2: metadata + models live on
    servers 0 and 1; events shard k lives on servers k, k+1 (mod 3)."""
    backends = [_memory_storage() for _ in range(3)]
    servers = [
        StorageServer(storage=b, host="127.0.0.1", port=0).start()
        for b in backends
    ]
    try:
        yield backends, servers, _client([s.port for s in servers],
                                         replicas=2)
    finally:
        for s in servers:
            s.stop()


def _instance(id="inst-1", status="COMPLETED"):
    t = _dt.datetime(2026, 3, 1, tzinfo=UTC)
    return EngineInstance(
        id=id, status=status, start_time=t, end_time=t,
        engine_id="eng", engine_version="0", engine_variant="default",
        engine_factory="tests.fake",
    )


def _seed_meta(client):
    app = client.apps().insert("repl-app")
    key = AccessKey.generate(app.id)
    client.access_keys().insert(key)
    ch = client.channels().insert("live", app.id)
    client.engine_manifests().insert(
        EngineManifest(id="eng", version="0", name="eng"))
    client.engine_instances().insert(_instance())
    client.models().insert(Model(id="inst-1", models=b"\x01\x02\x03"))
    return app, key, ch


def test_metadata_replicates_to_first_r_endpoints(three_replicated):
    backends, _, client = three_replicated
    app, key, ch = _seed_meta(client)

    # every record on BOTH metadata replicas with the SAME ids; none on
    # the third endpoint (it is an event shard only)
    for b in backends[:2]:
        got = b.apps().get_by_name("repl-app")
        assert got is not None and got.id == app.id
        assert b.access_keys().get(key.key) is not None
        assert [c.id for c in b.channels().get_by_app_id(app.id)] == [ch.id]
        assert b.engine_manifests().get("eng", "0") is not None
        assert b.engine_instances().get("inst-1") is not None
        assert b.models().get("inst-1").models == b"\x01\x02\x03"
    assert backends[2].apps().get_by_name("repl-app") is None
    assert backends[2].models().get("inst-1") is None


def test_reads_survive_metadata_home_death_writes_fail_loudly(
        three_replicated):
    backends, servers, client = three_replicated
    app, key, _ = _seed_meta(client)
    dead_url = f"http://127.0.0.1:{servers[0].port}"

    servers[0].stop()  # kill the metadata HOME

    # every read path the serving/deploy stack needs still answers
    assert client.apps().get_by_name("repl-app").id == app.id
    assert client.access_keys().get(key.key) is not None
    latest = client.engine_instances().get_latest_completed(
        "eng", "0", "default")
    assert latest is not None and latest.id == "inst-1"
    assert client.models().get("inst-1").models == b"\x01\x02\x03"

    # writes fail loudly, naming the dead endpoint
    with pytest.raises(StorageUnavailableError) as ei:
        client.apps().insert("another")
    assert dead_url in str(ei.value)
    with pytest.raises(StorageUnavailableError):
        client.engine_instances().insert(_instance(id="inst-2"))
    with pytest.raises(StorageUnavailableError):
        client.models().insert(Model(id="mx", models=b"zz"))
    # the failed instance/model writes left nothing behind anywhere
    assert backends[1].engine_instances().get("inst-2") is None
    assert backends[1].models().get("mx") is None

    # `pio status`: DEGRADED exit code — every tier still serving
    from predictionio_tpu.tools.cli import STATUS_DEGRADED, main as cli_main

    try:
        set_storage(client)
        assert cli_main(["status"]) == STATUS_DEGRADED
    finally:
        set_storage(None)


def test_engine_server_reload_survives_metadata_home_death(three_replicated):
    """A serving host must be able to /reload after the metadata home
    dies: get_latest_completed + the model blob both answer from the
    surviving replica (the done-criterion of VERDICT r3 item 1)."""
    from tests.test_servers import http, train_const
    from predictionio_tpu.serving.engine_server import EngineServer

    _, servers, client = three_replicated
    engine, _ = train_const(client)  # writes instance+model through
    # the replicated tier (all replicas up)
    es = EngineServer(engine, "const", host="127.0.0.1", port=0,
                      storage=client).start()
    try:
        base = f"http://127.0.0.1:{es.port}"
        assert http("POST", f"{base}/queries.json", {"mult": 5})[1] == \
            {"result": 15.0}

        servers[0].stop()  # metadata home dies

        status, _ = http("GET", f"{base}/reload")
        assert status == 200
        assert http("POST", f"{base}/queries.json", {"mult": 2})[1] == \
            {"result": 6.0}
    finally:
        es.stop()


def test_failed_metadata_insert_rolls_back(three_replicated):
    """A write that cannot reach the full replica set must leave no
    copy a read would serve (the event tier's rollback contract,
    applied to metadata)."""
    backends, servers, client = three_replicated

    servers[1].stop()  # kill the SUCCESSOR metadata replica

    # id-assigning insert: owner assigned the id, successor failed,
    # owner copy rolled back
    with pytest.raises(StorageUnavailableError):
        client.apps().insert("doomed")
    assert backends[0].apps().get_by_name("doomed") is None

    # successors-first writes: nothing ever landed on the owner
    with pytest.raises(StorageUnavailableError):
        client.engine_instances().insert(_instance(id="doomed-inst"))
    assert backends[0].engine_instances().get("doomed-inst") is None
    with pytest.raises(StorageUnavailableError):
        client.models().insert(Model(id="doomed-m", models=b"x"))
    assert backends[0].models().get("doomed-m") is None


def test_repair_meta_reconciles_diverged_replicas(three_replicated):
    backends, _, client = three_replicated
    app, key, ch = _seed_meta(client)

    # diverge by hand: the states partial failures leave behind
    backends[1].access_keys().delete(key.key)            # missing record
    backends[1].engine_instances().insert(_instance(id="orphan"))  # orphan
    stale = dataclasses.replace(app, description="stale")
    backends[1].apps().update(stale)                     # stale content
    backends[1].models().insert(Model(id="inst-1", models=b"CORRUPT"))

    stats = client.client_for("METADATA").repair_meta()
    assert stats["copied"] >= 3 and stats["deleted"] >= 1

    # post-repair: replica 1 mirrors the owner exactly
    assert backends[1].access_keys().get(key.key) is not None
    assert backends[1].engine_instances().get("orphan") is None
    assert backends[1].apps().get(app.id).description == app.description
    assert backends[1].models().get("inst-1").models == b"\x01\x02\x03"

    # a second repair finds nothing to do
    assert client.client_for("METADATA").repair_meta() == {"copied": 0, "deleted": 0}


def test_repair_meta_refuses_unreplicated():
    from predictionio_tpu.tools.commands import CommandError, repair_metadata

    backend = _memory_storage()
    server = StorageServer(storage=backend, host="127.0.0.1", port=0).start()
    try:
        client = _client([server.port, server.port])  # sharded, REPLICAS=1
        with pytest.raises(StorageError):
            client.client_for("METADATA").repair_meta()
        # through the command layer BOTH unreplicated shapes are the
        # same "nothing to check" CommandError (the CLI then reports
        # the tier as skipped instead of failing a completed event
        # repair — code-review regression)
        with pytest.raises(CommandError):
            repair_metadata(storage=client)
        with pytest.raises(CommandError):
            repair_metadata(storage=backend)  # memory: no repair surface
    finally:
        server.stop()


def test_storagerepair_cli_covers_both_tiers(three_replicated, capsys):
    """`pio storagerepair` reconciles the app's events AND the
    metadata/model replica set in one run."""
    backends, _, client = three_replicated
    app, key, _ = _seed_meta(client)
    client.events().init(app.id)
    backends[1].access_keys().delete(key.key)  # metadata divergence

    from predictionio_tpu.tools.cli import main as cli_main

    try:
        set_storage(client)
        assert cli_main(["storagerepair", "--appname", "repl-app"]) == 0
        out = capsys.readouterr().out
        assert "Event replica repair" in out
        assert "Metadata/model replica repair" in out
    finally:
        set_storage(None)
    assert backends[1].access_keys().get(key.key) is not None


def test_status_exit_codes_distinguish_tiers(three_replicated):
    """0 = all endpoints up; 2 = degraded but every tier serving;
    1 = some tier cannot answer (VERDICT r3 item 9)."""
    from predictionio_tpu.tools.cli import STATUS_DEGRADED, main as cli_main

    backends, servers, client = three_replicated
    try:
        set_storage(client)
        assert cli_main(["status"]) == 0

        # a pure event replica down: every shard still has a live
        # replica, metadata home untouched -> DEGRADED
        servers[2].stop()
        assert cli_main(["status"]) == STATUS_DEGRADED

        # two servers down: event shard 1 (replicas on 1 and 2) has no
        # live copy -> hard failure
        servers[1].stop()
        assert cli_main(["status"]) == 1
    finally:
        set_storage(None)


def test_status_exit_1_when_metadata_tier_dies():
    """Both metadata replicas down (events still fine on server 2 is
    impossible with R=2 over 3 servers — shard coverage also breaks —
    but the metadata tier must independently report FAILED)."""
    backends = [_memory_storage() for _ in range(3)]
    servers = [StorageServer(storage=b, host="127.0.0.1", port=0).start()
               for b in backends]
    client = _client([s.port for s in servers], replicas=2)
    try:
        set_storage(client)
        servers[0].stop()
        servers[1].stop()
        tiers = client.client_for("METADATA").health_tiers()
        assert tiers["metadata_serving"] is False
        from predictionio_tpu.tools.cli import main as cli_main

        assert cli_main(["status"]) == 1
    finally:
        set_storage(None)
        for s in servers:
            s.stop()


def test_repair_refuses_blank_owner(three_replicated):
    """Code-review regression: a re-provisioned BLANK metadata owner
    must never erase the surviving replicas records via repair."""
    backends, _, client = three_replicated
    app, key, _ = _seed_meta(client)

    # wipe the OWNER only (the re-provisioned-blank-host scenario)
    backends[0].apps().delete(app.id)
    backends[0].access_keys().delete(key.key)
    with pytest.raises(StorageError, match="repair refused"):
        client.client_for("METADATA").repair_meta()
    # the replica records survived
    assert backends[1].apps().get_by_name("repl-app") is not None

    # blank owner MODELS only: also refused
    backends[0].apps().put(app)           # restore records
    backends[0].access_keys().put(key)
    backends[0].models().delete("inst-1")
    with pytest.raises(StorageError, match="no model blobs"):
        client.client_for("METADATA").repair_meta()
    assert backends[1].models().get("inst-1") is not None
