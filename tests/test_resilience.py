"""Resilience subsystem: retry/deadline/breaker policies, chaos-driven
fault injection, admission control (429 + Retry-After), degraded-mode
serving, and SLO alert delivery (predictionio_tpu/resilience/*)."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from predictionio_tpu.data.storage import (
    Storage,
    StorageUnavailableError,
)
from predictionio_tpu.obs import health, metrics, slo
from predictionio_tpu.resilience import admission, alerts, chaos, policy
from predictionio_tpu.resilience.policy import (
    CircuitBreaker,
    CircuitOpenError,
    Policy,
    RetryBudgetExceeded,
)
from predictionio_tpu.serving import engine_server as engine_server_mod
from predictionio_tpu.serving.engine_server import EngineServer, MicroBatcher
from predictionio_tpu.serving.event_server import EventServer
from predictionio_tpu.serving.http import HTTPServerBase, JSONRequestHandler

from tests.test_health import _wait_for, get, get_json, train_const


def post(url, body=b"{}", headers=None, timeout=15):
    req = urllib.request.Request(
        url, data=body, method="POST",
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read().decode(), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode(), dict(e.headers)


# -- Policy: retry budget + full-jitter backoff --------------------------------

def test_backoff_full_jitter_bounds():
    """Jittered-backoff bounds: every delay for retry k lies in
    [0, min(cap, base * 2^k)], and the draws actually spread (full
    jitter, not a constant)."""
    p = Policy(backoff_base=0.2, backoff_cap=1.0)
    for attempt, ceiling in enumerate([0.2, 0.4, 0.8, 1.0, 1.0]):
        draws = [p.backoff_seconds(attempt) for _ in range(200)]
        assert all(0.0 <= d <= ceiling for d in draws), (attempt, ceiling)
        assert max(draws) > ceiling * 0.5  # the upper half is reachable
        assert min(draws) < ceiling * 0.5  # ...and so is the lower


def test_retry_budget_exhaustion():
    calls = {"n": 0}

    def always_down():
        calls["n"] += 1
        raise ConnectionRefusedError("nope")

    p = Policy(retries=3)
    with pytest.raises(ConnectionRefusedError):
        p.run(always_down, sleep=lambda s: None)
    assert calls["n"] == 4  # 1 attempt + 3 retries

    calls["n"] = 0
    with pytest.raises(RetryBudgetExceeded) as ei:
        p.run(always_down, sleep=lambda s: None, raise_exhausted=True)
    assert ei.value.attempts == 4
    assert isinstance(ei.value.last, ConnectionRefusedError)

    # non-idempotent: the budget is never spent
    calls["n"] = 0
    with pytest.raises(ConnectionRefusedError):
        p.run(always_down, idempotent=False, sleep=lambda s: None)
    assert calls["n"] == 1


def test_application_errors_are_not_retried():
    calls = {"n": 0}

    def bad_request():
        calls["n"] += 1
        raise ValueError("your fault, not the network's")

    with pytest.raises(ValueError):
        Policy(retries=5).run(bad_request, sleep=lambda s: None)
    assert calls["n"] == 1


def test_retry_success_after_transient_failures():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionResetError("blip")
        return "ok"

    assert Policy(retries=3).run(flaky, sleep=lambda s: None) == "ok"
    assert calls["n"] == 3


# -- circuit breaker lifecycle -------------------------------------------------

def test_breaker_open_half_open_close_lifecycle():
    br = CircuitBreaker("t-lifecycle", failure_threshold=2,
                        reset_timeout=0.08)
    assert br.state == policy.CLOSED and br.allow()
    br.record_failure()
    assert br.state == policy.CLOSED  # one failure is not an outage
    br.record_failure()
    assert br.state == policy.OPEN
    assert not br.allow()             # fail fast, no connect attempt
    assert br.retry_after() > 0

    time.sleep(0.1)
    assert br.allow()                 # the half-open probe
    assert br.state == policy.HALF_OPEN
    assert not br.allow()             # only one probe at a time
    br.record_failure()               # probe failed: re-open, re-arm
    assert br.state == policy.OPEN and not br.allow()

    time.sleep(0.1)
    assert br.allow()
    br.record_success()               # probe succeeded: recovery
    assert br.state == policy.CLOSED and br.allow()


def test_policy_fails_fast_while_circuit_open():
    br = CircuitBreaker("t-fast", failure_threshold=1, reset_timeout=60.0)
    br.record_failure()
    calls = {"n": 0}

    def fn():
        calls["n"] += 1

    with pytest.raises(CircuitOpenError) as ei:
        Policy().run(fn, breaker=br, sleep=lambda s: None)
    assert calls["n"] == 0            # the transport was never touched
    assert ei.value.retry_after > 0


def test_admitted_call_keeps_its_retry_budget():
    """A call admitted while closed retries through the circuit opening
    mid-call — that is what lets retries ride out the blip that opened
    it (new calls fail fast meanwhile)."""
    br = CircuitBreaker("t-midcall", failure_threshold=2, reset_timeout=60.0)
    calls = {"n": 0}

    def recovers_on_third():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionResetError("down")
        return "back"

    assert Policy(retries=3).run(recovers_on_third, breaker=br,
                                 sleep=lambda s: None) == "back"
    assert br.state == policy.CLOSED  # success closed it again


def test_breaker_state_gauge_and_health_probe():
    br = policy.breaker_for("t-gauge", failure_threshold=1,
                            reset_timeout=60.0)
    gauge = metrics.REGISTRY.get("pio_circuit_state")
    assert gauge.labels("t-gauge").value == 0.0
    br.record_failure()
    assert gauge.labels("t-gauge").value == 2.0
    # the circuit_breakers health probe reports open circuits DEGRADED
    assert "circuit_breakers" in health.REGISTRY.names()
    _, detail = health.REGISTRY.run()
    assert detail["circuit_breakers"]["status"] == "degraded"
    assert "t-gauge" in detail["circuit_breakers"]["reason"]
    br.record_success()
    assert gauge.labels("t-gauge").value == 0.0
    _, detail = health.REGISTRY.run()
    assert detail["circuit_breakers"]["status"] == "ok"


def test_rest_transport_circuit_opens_and_fails_fast():
    """Enough consecutive connection failures against a dead storage
    endpoint open its circuit; the NEXT call answers instantly with a
    circuit-open StorageUnavailableError (no connect, no timeout)."""
    from tests.test_rest_storage import _client_storage

    client = _client_storage(1)  # nothing listens on port 1
    # each idempotent read burns 1+3 attempts; two reads cross the
    # default threshold of 5 consecutive failures
    for _ in range(2):
        with pytest.raises(StorageUnavailableError):
            client.apps().get_all()
    base_url = "http://127.0.0.1:1"
    assert policy.breaker_for(base_url).state == policy.OPEN
    t0 = time.perf_counter()
    with pytest.raises(StorageUnavailableError) as ei:
        client.apps().get_all()
    assert "circuit open" in str(ei.value)
    assert time.perf_counter() - t0 < 0.1  # failed fast, not via timeouts


# -- chaos harness -------------------------------------------------------------

def test_chaos_spec_parsing():
    rules = chaos.parse_spec(
        "storage:latency:50ms,storage:error:0.25,batcher:hang:2s,"
        "train:error")
    assert [(r.site, r.kind, r.amount) for r in rules] == [
        ("storage", "latency", 0.05),
        ("storage", "error", 0.25),
        ("batcher", "hang", 2.0),
        ("train", "error", 1.0),
    ]
    for bad in ("storage", "storage:latency", "storage:explode:1",
                "storage:error:1.5", "storage:latency:soon"):
        with pytest.raises(ValueError):
            chaos.parse_spec(bad)


def test_chaos_injection_latency_and_error():
    chaos.configure("seam:latency:30ms")
    t0 = time.perf_counter()
    chaos.inject("seam")
    assert time.perf_counter() - t0 >= 0.03
    chaos.inject("other-seam")  # no rules for it: no-op

    chaos.configure("seam:error:1")
    with pytest.raises(chaos.ChaosError) as ei:
        chaos.inject("seam")
    # the injected failure classifies as a CONNECTION failure — the
    # breaker/retry machinery cannot tell it from a real outage
    assert isinstance(ei.value, ConnectionError)
    counted = metrics.REGISTRY.get("pio_chaos_injections_total")
    assert counted.labels("seam", "error").value >= 1

    chaos.clear()
    chaos.inject("seam")  # cleared: no-op


def test_chaos_env_and_admin_mutation(monkeypatch):
    monkeypatch.setenv("PIO_CHAOS", "storage:latency:1ms")
    assert [r.site for r in chaos.configure_from_env()] == ["storage"]
    state = chaos.apply_admin({"add": "batcher:error:0.5"})
    assert len(state["rules"]) == 2 and state["enabled"]
    state = chaos.apply_admin({"clear": "storage"})
    assert [r["site"] for r in state["rules"]] == ["batcher"]
    state = chaos.apply_admin({"clear": True})
    assert state == chaos.describe() and not state["enabled"]
    with pytest.raises(ValueError):
        chaos.apply_admin({})
    with pytest.raises(ValueError):
        chaos.apply_admin({"spec": "nope"})


def test_server_start_does_not_revert_admin_chaos(monkeypatch):
    """Explicit configuration outranks the env for the process's life:
    a second in-process server start (configure_from_env again) must
    not re-enable injection an operator turned off."""
    monkeypatch.setenv("PIO_CHAOS", "storage:error:0.1")
    assert [r.site for r in chaos.configure_from_env()] == ["storage"]
    chaos.clear()  # the operator's decision
    assert chaos.configure_from_env() == []  # later boot: stays off
    chaos.configure("batcher:latency:1ms")
    assert [r.site for r in chaos.configure_from_env()] == ["batcher"]


def test_admin_chaos_endpoint_and_cli(memory_storage, capsys):
    server = EventServer(storage=memory_storage, host="127.0.0.1",
                         port=0).start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        status, body = get_json(f"{base}/admin/chaos")
        assert status == 200 and body["enabled"] is False
        status, _, _ = post(f"{base}/admin/chaos",
                            json.dumps({"spec": "storage:latency:1ms"})
                            .encode())
        assert status == 200
        assert [r.spec() for r in chaos.active()] == ["storage:latency:0.001s"]
        status, _, _ = post(f"{base}/admin/chaos", b'{"spec": "bad"}')
        assert status == 400

        from predictionio_tpu.tools.cli import main

        assert main(["chaos", "--url", base]) == 0
        assert "storage" in capsys.readouterr().out
        assert main(["chaos", "--url", base, "--clear"]) == 0
        assert chaos.active() == []
    finally:
        server.stop()


def test_admin_chaos_requires_bearer_when_token_set(memory_storage,
                                                   monkeypatch):
    server = EventServer(storage=memory_storage, host="127.0.0.1",
                         port=0).start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        monkeypatch.setenv("PIO_ADMIN_TOKEN", "s3cret")
        assert get(f"{base}/admin/chaos")[0] == 401
        assert get(f"{base}/admin/resilience")[0] == 401
        auth = {"Authorization": "Bearer s3cret"}
        assert get(f"{base}/admin/chaos", headers=auth)[0] == 200
        status, body = get_json(f"{base}/admin/resilience")
        assert status == 401
        status, text, _ = get(f"{base}/admin/resilience", headers=auth)
        assert status == 200 and "circuits" in json.loads(text)
    finally:
        server.stop()


# -- admission controller (unit) -----------------------------------------------

def test_admission_controller_signals():
    signals = {"depth": 0, "inflight": 0.0, "burn": 0.0}
    ctl = admission.AdmissionController(
        "t", queue_depth=lambda: signals["depth"],
        inflight=lambda: signals["inflight"],
        burn=lambda: signals["burn"],
        max_queue_depth=4, max_inflight=8, max_burn=14.4)
    assert ctl.check() is None

    signals["depth"] = 4
    decision = ctl.check()
    assert decision.reason == "queue_depth" and decision.retry_after >= 1
    signals["depth"] = 40
    assert ctl.check().retry_after > 1  # deeper backlog, longer advice

    signals["depth"] = 0
    # the gauge counts the current request itself: AT the limit is
    # admitted (otherwise inflight=1 would shed everything), one past
    # it is shed
    signals["inflight"] = 8
    assert ctl.check() is None
    signals["inflight"] = 9
    assert ctl.check().reason == "inflight"

    signals["inflight"] = 0.0
    signals["burn"] = 20.0
    decision = ctl.check()
    assert decision.reason == "burn_rate" and decision.retry_after >= 10

    # declarative overrides; 0 disables a signal
    ctl.configure({"burn": 0, "queue_depth": 2})
    assert ctl.check() is None
    signals["depth"] = 2
    assert ctl.check().reason == "queue_depth"
    shed = metrics.REGISTRY.get("pio_shed_total")
    assert shed.labels("t", "queue_depth").value >= 2
    snap = ctl.snapshot()
    assert snap["limits"]["queue_depth"] == 2 and snap["shedTotal"] >= 4


# -- engine server integration: shedding under synthetic overload -------------

def test_engine_server_sheds_with_429_under_overload(memory_storage):
    """Chaos-injected dispatch latency + a tight queue limit: the
    flood gets a mix of 200s and 429s (with Retry-After), and the p99
    of ACCEPTED requests stays bounded — overload degrades into
    explicit shed, not queueing collapse."""
    engine, _ = train_const(memory_storage)
    server = EngineServer(engine, "const", host="127.0.0.1", port=0,
                          storage=memory_storage, max_batch=1).start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        server.admission.configure(
            {"queue_depth": 2, "inflight": 0, "burn": 0})
        chaos.configure("batcher:latency:0.15")
        results = []
        lock = threading.Lock()

        def one_query():
            t0 = time.perf_counter()
            status, _, headers = post(f"{base}/queries.json",
                                      b'{"mult": 2}')
            with lock:
                results.append(
                    (status, time.perf_counter() - t0, headers))

        # wave 1 saturates the (slowed) dispatcher and builds a queue;
        # wave 2 arrives into the backlog and meets the shedder
        wave1 = [threading.Thread(target=one_query) for _ in range(4)]
        for t in wave1:
            t.start()
        time.sleep(0.1)  # inside wave 1's ~0.6s drain window
        wave2 = [threading.Thread(target=one_query) for _ in range(12)]
        for t in wave2:
            t.start()
        for t in wave1 + wave2:
            t.join()
        statuses = [r[0] for r in results]
        assert statuses.count(200) >= 1, statuses
        assert statuses.count(429) >= 1, statuses
        for status, _, headers in results:
            if status == 429:
                assert int(headers["Retry-After"]) >= 1
        accepted = sorted(r[1] for r in results if r[0] == 200)
        # queue cap 2 + one in dispatch at 0.15s each: the accepted
        # tail is a few dispatches deep, never the whole flood's wait
        assert accepted[-1] < 3.0, accepted
        shed = metrics.REGISTRY.get("pio_shed_total")
        assert shed.labels("engine", "queue_depth").value >= 1
        # the shed is reconstructable from the status page
        _, body = get_json(base + "/")
        assert body["admission"]["shedTotal"] >= 1
    finally:
        chaos.clear()
        server.stop()


# -- engine server integration: degraded-mode serving --------------------------

def test_degraded_serving_with_killed_sqlite_backend(tmp_path):
    """Acceptance: storage dies under a live engine server -> the
    storage circuit opens, /readyz reports DEGRADED (200, not 503/
    FAILED), queries keep answering from the last-loaded model with an
    X-PIO-Degraded stamp, and their latency stays bounded while the
    breaker is open."""
    storage = Storage.from_env({
        "PIO_STORAGE_SOURCES_SQL_TYPE": "sqlite",
        "PIO_STORAGE_SOURCES_SQL_PATH": str(tmp_path / "pio.db"),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQL",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQL",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SQL",
    })
    engine, _ = train_const(storage)
    server = EngineServer(engine, "const", host="127.0.0.1", port=0,
                          storage=storage).start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        # healthy baseline: ready, no degraded stamp
        status, body = get_json(f"{base}/readyz")
        assert status == 200 and body["probes"]["storage"]["status"] == "ok"
        status, _, headers = post(f"{base}/queries.json", b'{"mult": 3}')
        assert status == 200 and "X-PIO-Degraded" not in headers

        # kill the backend: every storage touch now raises
        storage.client_for("METADATA").close()

        # consecutive readiness probes trip the storage circuit
        # (failure_threshold=2); readyz stays 200 throughout — storage
        # loss with a loaded model is DEGRADED, never FAILED
        for _ in range(3):
            status, body = get_json(f"{base}/readyz")
            assert status == 200, body
            assert body["status"] in ("ok", "degraded")
            assert body["probes"]["storage"]["status"] in (
                "ok", "degraded")
        assert body["status"] == "degraded"
        assert "degraded" in body["probes"]["storage"]["reason"].lower() \
            or "circuit" in body["probes"]["storage"]["reason"]
        assert server._storage_breaker.state == policy.OPEN
        gauge = metrics.REGISTRY.get("pio_circuit_state")
        assert gauge.labels("storage:const").value == 2.0

        # the last-loaded model still answers, stamped + bounded
        latencies = []
        for _ in range(8):
            t0 = time.perf_counter()
            status, text, headers = post(f"{base}/queries.json",
                                         b'{"mult": 3}')
            latencies.append(time.perf_counter() - t0)
            assert status == 200
            assert json.loads(text) == {"result": 9.0}
            assert "last-loaded instance" in headers["X-PIO-Degraded"]
        assert sorted(latencies)[-1] < 2.0, latencies
        # /reload cannot work without storage — and says so (an HTTP
        # error answer, never a crashed connection)
        status, _ = get_json(f"{base}/reload")
        assert status in (404, 503)
        # the status page names the condition
        _, body = get_json(base + "/")
        assert body["degraded"] and body["storageCircuit"]["state"] == "open"
    finally:
        server.stop()


def test_degraded_mode_recovers_when_storage_returns(memory_storage,
                                                     monkeypatch):
    """Recovery closes the loop: chaos-injected storage errors open the
    circuit; clearing them lets the half-open probe succeed, serving
    leaves degraded mode with no restart."""
    monkeypatch.setenv("PIO_BREAKER_RESET_SEC", "0.1")
    engine, _ = train_const(memory_storage)
    server = EngineServer(engine, "const", host="127.0.0.1", port=0,
                          storage=memory_storage).start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        chaos.configure("storage:error:1")
        for _ in range(3):
            status, body = get_json(f"{base}/readyz")
            assert status == 200
        assert body["status"] == "degraded"
        assert server.degraded_reason() is not None
        _, _, headers = post(f"{base}/queries.json", b'{"mult": 1}')
        assert "X-PIO-Degraded" in headers

        chaos.clear()
        time.sleep(0.15)  # past the reset window: next probe is let through
        status, body = get_json(f"{base}/readyz")
        assert status == 200 and body["probes"]["storage"]["status"] == "ok"
        assert server.degraded_reason() is None
        _, _, headers = post(f"{base}/queries.json", b'{"mult": 1}')
        assert "X-PIO-Degraded" not in headers
    finally:
        chaos.clear()
        server.stop()


# -- chaos hang vs the dispatch watchdog ---------------------------------------

def test_watchdog_still_fires_on_chaos_hang(monkeypatch):
    """A true hang (chaos ``batcher:hang``) is the watchdog's job, not
    admission control's: the stall fires while the dispatch is still
    hung."""
    tight = health.Watchdog("dispatch-chaos-test", min_seconds=0.01,
                            min_history=1, factor=2.0)
    monkeypatch.setattr(engine_server_mod, "_DISPATCH_WATCHDOG", tight)

    def stall_count():
        return metrics.REGISTRY.get(
            "pio_watchdog_stall_total").labels("dispatch-chaos-test").value

    batcher = MicroBatcher(lambda ps: ps, lambda p: p)
    try:
        batcher.submit("warm")  # builds the trailing-median history
        before = stall_count()
        chaos.configure("batcher:hang:0.3")
        done = threading.Event()

        def submit_hung():
            try:
                batcher.submit("hung", timeout=5)
            finally:
                done.set()

        threading.Thread(target=submit_hung, daemon=True).start()
        assert _wait_for(lambda: stall_count() == before + 1)
        chaos.clear()
        assert done.wait(5)  # the hang ends; the waiter is answered
    finally:
        chaos.clear()
        batcher.stop()


# -- SLO alert webhook delivery ------------------------------------------------

class _WebhookSink:
    """Local HTTP sink; optionally 503s the first N deliveries."""

    def __init__(self, fail_first=0):
        self.payloads = []
        self.hits = 0
        sink = self

        class Handler(JSONRequestHandler):
            server_version = "WebhookSink/0.1"

            def do_POST(self):
                body = self._read_body()
                sink.hits += 1
                if sink.hits <= fail_first:
                    self._send(503, {"message": "not yet"})
                else:
                    sink.payloads.append(json.loads(body))
                    self._send(200, {"message": "ok"})

        self.server = HTTPServerBase("127.0.0.1", 0, Handler).start()

    @property
    def url(self):
        return f"http://127.0.0.1:{self.server.port}/hook"

    def stop(self):
        self.server.stop()


def _availability_monitor():
    mon = slo.SLOMonitor([slo.SLO(name="t-hook", kind="availability",
                                  metric="nonexistent", objective=0.99)])
    t0 = 5_000_000.0
    # long healthy history so both fast windows can burn hot later
    for i in range(75):
        mon.record("t-hook", t0 + i * 60, 600.0 * i, 600.0 * i)
    return mon, t0 + 74 * 60, 600.0 * 74


def test_webhook_fires_on_alert_transitions():
    sink = _WebhookSink()
    hook = alerts.AlertWebhook(sink.url, policy=Policy(
        deadline=5.0, retries=2, backoff_base=0.01, backoff_cap=0.05))
    slo.add_alert_listener(hook.on_transition)
    mon, t_last, n = _availability_monitor()

    def mine():
        # the listener is global: the process-wide MONITOR may fire its
        # own transitions during the test — count only this SLO's pages
        return [p for p in sink.payloads if p["slo"] == "t-hook"]

    try:
        # a total outage: every request in the last hour+ is an error
        mon.record("t-hook", t_last + 60, n, n + 5000)
        mon.evaluate(now=t_last + 60)
        assert _wait_for(lambda: len(mine()) >= 1)
        assert mine()[0]["state"] == "firing"
        assert mine()[0]["slo_report"]["state"] == "firing"
        # steady evaluation while still firing: no duplicate page
        mon.record("t-hook", t_last + 120, n, n + 5000)
        mon.evaluate(now=t_last + 120)
        # recovery: lots of healthy traffic dilutes every window
        good = n + 900_000
        mon.record("t-hook", t_last + 22000, good, good + 5000)
        mon.evaluate(now=t_last + 22000)
        assert _wait_for(lambda: len(mine()) >= 2)
        assert mine()[-1]["state"] == "resolved"
        assert len(mine()) == 2  # one per TRANSITION, not per tick
        family = metrics.REGISTRY.get("pio_alert_webhook_total")
        assert family.labels("ok").value >= 2
    finally:
        slo.remove_alert_listener(hook.on_transition)
        hook.stop()
        sink.stop()


def test_webhook_retries_flaky_sink_through_policy():
    sink = _WebhookSink(fail_first=2)
    hook = alerts.AlertWebhook(sink.url, policy=Policy(
        deadline=5.0, retries=4, backoff_base=0.01, backoff_cap=0.05))
    try:
        assert hook.deliver({"type": "slo_alert", "slo": "t",
                             "state": "firing"}) is True
        assert sink.hits == 3  # two 503s retried through, then delivered
    finally:
        hook.stop()
        sink.stop()


def test_webhook_starts_from_env(monkeypatch):
    sink = _WebhookSink()
    monkeypatch.setenv("PIO_ALERT_WEBHOOK_URL", sink.url)
    try:
        hook = alerts.start_from_env()
        assert hook is not None
        assert alerts.start_from_env() is hook  # idempotent
        assert hook.on_transition in slo._alert_listeners
    finally:
        alerts.stop()
        sink.stop()
    assert hook.on_transition not in slo._alert_listeners


def test_find_does_not_backoff_against_an_open_circuit():
    """find()'s whole-scan retry loop gives up immediately on a
    circuit-open failure — backoff-sleeping against a breaker that is
    guaranteed to fail fast would defeat its purpose."""
    from tests.test_rest_storage import _client_storage

    client = _client_storage(1)
    for _ in range(2):  # open the endpoint's circuit
        with pytest.raises(StorageUnavailableError):
            client.apps().get_all()
    assert policy.breaker_for("http://127.0.0.1:1").state == policy.OPEN
    t0 = time.perf_counter()
    with pytest.raises(StorageUnavailableError) as ei:
        client.events().find(app_id=1)
    assert "circuit open" in str(ei.value)
    assert time.perf_counter() - t0 < 0.1  # no backoff sleeps happened


def test_snapshot_cadence_evaluates_slos():
    """The flight-recorder cadence hook must EVALUATE, not just sample:
    evaluation is what refreshes the burn gauges (the shed signal) and
    fires alert transitions (the webhook) on an unattended server."""
    import predictionio_tpu.obs.flight as flight_mod

    for _name, fn in flight_mod._snapshot_listeners:
        fn()
    family = metrics.REGISTRY.get("pio_slo_burn_rate")
    labels = {values for values, _ in family.children()}
    assert ("serving-latency", "5m") in labels


# -- declarative SLO + shedding config -----------------------------------------

def test_declarative_slo_configuration():
    try:
        slo.configure({"latency_ms": 50, "latency_objective": 0.999,
                       "availability_objective": 0.995})
        by_name = {s.name: s for s in slo.MONITOR.slos()}
        assert by_name["serving-latency"].threshold_ms == 50
        assert by_name["serving-latency"].objective == 0.999
        assert by_name["http-availability"].objective == 0.995
    finally:
        slo.configure({})  # back to env defaults
    by_name = {s.name: s for s in slo.MONITOR.slos()}
    assert by_name["serving-latency"].threshold_ms == 100.0


def test_slo_file_loading(tmp_path, monkeypatch):
    conf = tmp_path / "slo.json"
    conf.write_text(json.dumps({"latency_ms": 42,
                                "shed": {"queue_depth": 9}}))
    monkeypatch.setenv("PIO_SLO_FILE", str(conf))
    monkeypatch.setattr(slo, "_file_config_path", None)
    monkeypatch.setattr(slo, "_file_config", None)
    try:
        loaded = slo.configure_from_env()
        assert loaded["shed"] == {"queue_depth": 9}
        by_name = {s.name: s for s in slo.MONITOR.slos()}
        assert by_name["serving-latency"].threshold_ms == 42
    finally:
        slo.configure({})


def test_engine_variant_slo_block_reaches_admission(memory_storage):
    from predictionio_tpu.workflow.variant import EngineVariant

    variant = EngineVariant.from_dict({
        "engineFactory": "x.Y",
        "slo": {"latency_ms": 75,
                "shed": {"queue_depth": 7, "inflight": 11}},
    })
    assert variant.slo_conf()["latency_ms"] == 75
    with pytest.raises(ValueError):
        EngineVariant.from_dict(
            {"engineFactory": "x.Y", "slo": ["nope"]}).slo_conf()

    engine, _ = train_const(memory_storage)
    server = EngineServer(engine, "const", host="127.0.0.1", port=0,
                          storage=memory_storage,
                          slo_conf=variant.slo_conf())
    try:
        assert server.admission.max_queue_depth == 7
        assert server.admission.max_inflight == 11
        by_name = {s.name: s for s in slo.MONITOR.slos()}
        assert by_name["serving-latency"].threshold_ms == 75
    finally:
        slo.configure({})
        server.stop()


def test_variant_slo_block_layers_over_slo_file(memory_storage, tmp_path,
                                                monkeypatch):
    """A variant block overrides only the keys it names: the file's
    other objectives survive instead of snapping back to env
    defaults."""
    conf = tmp_path / "slo.json"
    conf.write_text(json.dumps({"latency_ms": 42}))
    monkeypatch.setenv("PIO_SLO_FILE", str(conf))
    monkeypatch.setattr(slo, "_file_config_path", None)
    monkeypatch.setattr(slo, "_file_config", None)
    engine, _ = train_const(memory_storage)
    server = EngineServer(engine, "const", host="127.0.0.1", port=0,
                          storage=memory_storage,
                          slo_conf={"availability_objective": 0.95})
    try:
        by_name = {s.name: s for s in slo.MONITOR.slos()}
        assert by_name["serving-latency"].threshold_ms == 42
        assert by_name["http-availability"].objective == 0.95
    finally:
        slo.configure({})
        server.stop()
