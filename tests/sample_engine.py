"""Deterministic toy DASE components for pipeline-wiring tests.

Equivalent of the reference's keystone test asset SampleEngine.scala
(core/src/test/.../controller/SampleEngine.scala, 463 LoC): every
component tags its output with its id so tests can assert the exact
wiring of train/eval/serve paths.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Tuple

from predictionio_tpu.core import (
    Algorithm,
    DataSource,
    Preparator,
    SanityCheck,
    Serving,
)
from predictionio_tpu.core.params import Params
from predictionio_tpu.core.persistent_model import LocalFileSystemPersistentModel


@dataclass
class IdParams(Params):
    id: int = 0
    fail_sanity: bool = False


# -- data types tagged with their producers ---------------------------------

@dataclass
class TD(SanityCheck):
    ds_id: int
    fail_sanity: bool = False

    def sanity_check(self):
        if self.fail_sanity:
            raise ValueError(f"TD sanity failure (ds {self.ds_id})")


@dataclass
class EI:
    ds_id: int
    fold: int


@dataclass
class PD(SanityCheck):
    prep_id: int
    td: TD
    fail_sanity: bool = False

    def sanity_check(self):
        if self.fail_sanity:
            raise ValueError(f"PD sanity failure (prep {self.prep_id})")


@dataclass
class Model:
    algo_id: int
    pd: PD


@dataclass
class Query:
    q: int


@dataclass
class Prediction:
    algo_id: int
    q: int


@dataclass
class Actual:
    q: int


# -- components --------------------------------------------------------------

class DataSource0(DataSource):
    """Returns TD tagged with its id; k-fold eval data (2 folds, 2 queries)."""

    def __init__(self, params: IdParams):
        super().__init__(params)

    def read_training(self, ctx) -> TD:
        return TD(ds_id=self.params.id, fail_sanity=self.params.fail_sanity)

    def read_eval(self, ctx):
        folds = []
        for fold in range(2):
            td = TD(ds_id=self.params.id)
            ei = EI(ds_id=self.params.id, fold=fold)
            qa = [(Query(q=10 * fold + j), Actual(q=10 * fold + j)) for j in range(2)]
            folds.append((td, ei, qa))
        return folds


class Preparator0(Preparator):
    def __init__(self, params: IdParams):
        super().__init__(params)

    def prepare(self, ctx, td: TD) -> PD:
        return PD(prep_id=self.params.id, td=td, fail_sanity=self.params.fail_sanity)


class Algo0(Algorithm):
    def __init__(self, params: IdParams):
        super().__init__(params)

    def train(self, ctx, pd: PD) -> Model:
        return Model(algo_id=self.params.id, pd=pd)

    def predict(self, model: Model, query: Query) -> Prediction:
        return Prediction(algo_id=model.algo_id, q=query.q)


class AlgoNoParams(Algorithm):
    """Zero-arg ctor — exercises Doer.create's two-ctor protocol."""

    def train(self, ctx, pd: PD) -> Model:
        return Model(algo_id=-1, pd=pd)

    def predict(self, model: Model, query: Query) -> Prediction:
        return Prediction(algo_id=-1, q=query.q)


@dataclass
class PersistentModel0(LocalFileSystemPersistentModel):
    algo_id: int = 0


class AlgoPersistent(Algorithm):
    """Model persists itself via the PersistentModel path."""

    def __init__(self, params: IdParams):
        super().__init__(params)

    def train(self, ctx, pd: PD) -> PersistentModel0:
        return PersistentModel0(algo_id=self.params.id)

    def predict(self, model: PersistentModel0, query: Query) -> Prediction:
        return Prediction(algo_id=model.algo_id, q=query.q)


class Serving0(Serving):
    def __init__(self, params: IdParams):
        super().__init__(params)

    def serve(self, query: Query, predictions) -> Prediction:
        # tag-combining: sum of algo ids proves all algorithms were consulted
        return Prediction(
            algo_id=sum(p.algo_id for p in predictions), q=query.q
        )
