"""Event + Engine server HTTP behavior
(ref specs: EventServiceSpec.scala:33, webhook connector specs,
CreateServer routes)."""

import json
import time
import urllib.error
import urllib.request
from dataclasses import dataclass

import pytest

from predictionio_tpu.core import Algorithm, DataSource, Engine, FirstServing, IdentityPreparator
from predictionio_tpu.core.params import EngineParams, Params
from predictionio_tpu.data.metadata import AccessKey
from predictionio_tpu.serving.engine_server import EngineServer
from predictionio_tpu.serving.event_server import EventServer
from predictionio_tpu.workflow.train import run_train


def http(method, url, body=None, form=False):
    data = None
    headers = {}
    if body is not None:
        if form:
            from urllib.parse import urlencode

            data = urlencode(body).encode()
            headers["Content-Type"] = "application/x-www-form-urlencoded"
        else:
            data = json.dumps(body).encode()
            headers["Content-Type"] = "application/json"
    req = urllib.request.Request(url, data=data, headers=headers, method=method)
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, json.loads(resp.read() or b"null")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"null")


@pytest.fixture()
def event_server(memory_storage):
    app = memory_storage.apps().insert("srv-app")
    memory_storage.events().init(app.id)
    key = AccessKey.generate(app.id)
    memory_storage.access_keys().insert(key)
    server = EventServer(storage=memory_storage, host="127.0.0.1", port=0).start()
    yield server, app, key
    server.stop()


def test_event_server_alive_and_auth(event_server):
    server, app, key = event_server
    base = f"http://127.0.0.1:{server.port}"
    assert http("GET", f"{base}/")[1] == {"status": "alive"}
    status, body = http("POST", f"{base}/events.json", {"event": "rate"})
    assert status == 401
    status, body = http("POST", f"{base}/events.json?accessKey=WRONG", {"event": "rate"})
    assert status == 401
    assert body["message"] == "Invalid accessKey."


def test_event_crud_over_http(event_server):
    server, app, key = event_server
    base = f"http://127.0.0.1:{server.port}/events"
    auth = f"accessKey={key.key}"
    status, body = http(
        "POST",
        f"{base}.json?{auth}",
        {
            "event": "rate",
            "entityType": "user",
            "entityId": "u1",
            "targetEntityType": "item",
            "targetEntityId": "i1",
            "properties": {"rating": 5},
            "eventTime": "2026-01-01T00:00:00Z",
        },
    )
    assert status == 201
    event_id = body["eventId"]
    status, body = http("GET", f"{base}/{event_id}.json?{auth}")
    assert status == 200
    assert body["event"] == "rate" and body["properties"] == {"rating": 5}
    assert body["eventTime"] == "2026-01-01T00:00:00Z"
    status, body = http("DELETE", f"{base}/{event_id}.json?{auth}")
    assert status == 200 and body == {"message": "Found"}
    assert http("GET", f"{base}/{event_id}.json?{auth}")[0] == 404
    assert http("DELETE", f"{base}/{event_id}.json?{auth}")[0] == 404


def test_event_validation_and_whitelist(event_server):
    server, app, key = event_server
    base = f"http://127.0.0.1:{server.port}/events.json"
    status, body = http(
        "POST", f"{base}?accessKey={key.key}",
        {"event": "$bogus", "entityType": "user", "entityId": "u1"},
    )
    assert status == 400
    # whitelist-restricted key
    restricted = AccessKey.generate(app.id, events=["view"])
    server.core.storage.access_keys().insert(restricted)
    status, body = http(
        "POST", f"{base}?accessKey={restricted.key}",
        {"event": "buy", "entityType": "user", "entityId": "u1"},
    )
    assert status == 403
    status, _ = http(
        "POST", f"{base}?accessKey={restricted.key}",
        {"event": "view", "entityType": "user", "entityId": "u1"},
    )
    assert status == 201


def test_event_query_filters(event_server):
    server, app, key = event_server
    base = f"http://127.0.0.1:{server.port}/events.json"
    auth = f"accessKey={key.key}"
    for i, (name, uid) in enumerate([("rate", "u1"), ("rate", "u2"), ("buy", "u1")]):
        http("POST", f"{base}?{auth}", {
            "event": name, "entityType": "user", "entityId": uid,
            "eventTime": f"2026-01-01T00:0{i}:00Z",
        })
    status, body = http("GET", f"{base}?{auth}")
    assert status == 200 and len(body) == 3
    status, body = http("GET", f"{base}?{auth}&event=rate")
    assert len(body) == 2
    status, body = http("GET", f"{base}?{auth}&entityType=user&entityId=u1&reversed=true&limit=1")
    assert body[0]["event"] == "buy"
    # reversed without entity -> 400 (ref: EventAPI reversed constraint)
    assert http("GET", f"{base}?{auth}&reversed=true")[0] == 400
    # half-open window
    status, body = http(
        "GET", f"{base}?{auth}&startTime=2026-01-01T00:01:00Z&untilTime=2026-01-01T00:02:00Z"
    )
    assert len(body) == 1 and body[0]["entityId"] == "u2"
    assert http("GET", f"{base}?{auth}&startTime=garbage")[0] == 400
    # no match -> 404
    assert http("GET", f"{base}?{auth}&event=nope")[0] == 404


def test_channels_over_http(event_server):
    server, app, key = event_server
    ch = server.core.storage.channels().insert("live", app.id)
    server.core.storage.events().init(app.id, ch.id)
    base = f"http://127.0.0.1:{server.port}/events.json"
    http("POST", f"{base}?accessKey={key.key}&channel=live",
         {"event": "rate", "entityType": "user", "entityId": "u9"})
    status, body = http("GET", f"{base}?accessKey={key.key}&channel=live")
    assert len(body) == 1 and body[0]["entityId"] == "u9"
    # default channel unaffected
    assert http("GET", f"{base}?accessKey={key.key}")[0] == 404
    assert http("GET", f"{base}?accessKey={key.key}&channel=nope")[0] == 400


def test_stats_endpoint(event_server):
    server, app, key = event_server
    base = f"http://127.0.0.1:{server.port}"
    http("POST", f"{base}/events.json?accessKey={key.key}",
         {"event": "rate", "entityType": "user", "entityId": "u1"})
    http("POST", f"{base}/events.json?accessKey={key.key}", {"event": "$bogus",
         "entityType": "user", "entityId": "u1"})
    status, body = http("GET", f"{base}/stats.json?accessKey={key.key}")
    assert status == 200
    counts = {(c["status"], c["event"]): c["count"] for b in body["buckets"] for c in b["counts"]}
    assert counts[(201, "rate")] == 1
    assert counts[(400, "$bogus")] == 1


def test_webhooks(event_server):
    server, app, key = event_server
    base = f"http://127.0.0.1:{server.port}/webhooks"
    auth = f"accessKey={key.key}"
    # GET existence checks (ref: EventAPI webhook GET routes)
    assert http("GET", f"{base}/segmentio.json?{auth}")[0] == 200
    assert http("GET", f"{base}/nope.json?{auth}")[0] == 404
    assert http("GET", f"{base}/mailchimp?{auth}")[0] == 200
    # auth required even for GET; non-GET/POST methods rejected
    assert http("GET", f"{base}/segmentio.json")[0] == 401
    assert http("DELETE", f"{base}/segmentio.json?{auth}", {"type": "identify"})[0] == 405
    # segmentio identify (ref: SegmentIOConnector)
    status, body = http("POST", f"{base}/segmentio.json?{auth}", {
        "type": "identify", "userId": "u42",
        "timestamp": "2026-02-01T10:00:00Z",
        "traits": {"email": "x@y.z"},
    })
    assert status == 201
    ev = server.core.storage.events().find(app.id, event_names=["identify"])[0]
    assert ev.entity_id == "u42"
    assert ev.properties.get("traits", dict) == {"email": "x@y.z"}
    # unknown segmentio type -> 400
    status, body = http("POST", f"{base}/segmentio.json?{auth}",
                        {"type": "track", "userId": "u", "timestamp": "2026-01-01T00:00:00Z"})
    assert status == 400
    # mailchimp subscribe form (ref: MailChimpConnector)
    fields = {
        "type": "subscribe", "fired_at": "2026-03-26 21:35:57",
        "data[id]": "8a25ff1d98", "data[list_id]": "a6b5da1054",
        "data[email]": "api@mailchimp.com", "data[email_type]": "html",
        "data[merges][EMAIL]": "api@mailchimp.com",
        "data[merges][FNAME]": "MailChimp", "data[merges][LNAME]": "API",
        "data[merges][INTERESTS]": "Group1,Group2",
        "data[ip_opt]": "10.20.10.30", "data[ip_signup]": "10.20.10.30",
    }
    status, body = http("POST", f"{base}/mailchimp?{auth}", fields, form=True)
    assert status == 201
    ev = server.core.storage.events().find(app.id, event_names=["subscribe"])[0]
    assert ev.target_entity_id == "a6b5da1054"
    assert ev.event_time.year == 2026 and ev.event_time.hour == 21
    # missing type -> 400
    assert http("POST", f"{base}/mailchimp?{auth}", {"x": "1"}, form=True)[0] == 400


# ---------------------------------------------------------------------------
# engine server
# ---------------------------------------------------------------------------

@dataclass
class ConstParams(Params):
    value: float = 1.0


class ConstDataSource(DataSource):
    def __init__(self, params: ConstParams):
        super().__init__(params)

    def read_training(self, ctx):
        return self.params.value


class ConstAlgo(Algorithm):
    def __init__(self, params: ConstParams):
        super().__init__(params)

    def train(self, ctx, pd):
        return pd + self.params.value

    def predict(self, model, query):
        return {"result": model * query["mult"]}


def const_engine():
    return Engine(ConstDataSource, IdentityPreparator, {"const": ConstAlgo}, FirstServing)


def train_const(storage, ds_value=1.0, algo_value=2.0):
    engine = const_engine()
    ep = EngineParams(
        data_source_params=("", ConstParams(value=ds_value)),
        preparator_params=("", None),
        algorithm_params_list=[("const", ConstParams(value=algo_value))],
        serving_params=("", None),
    )
    return engine, run_train(engine, ep, engine_id="const", storage=storage)


@pytest.fixture()
def engine_server(memory_storage):
    engine, _ = train_const(memory_storage)  # model = 1 + 2 = 3
    server = EngineServer(
        engine, "const", host="127.0.0.1", port=0, storage=memory_storage
    ).start()
    yield server, engine, memory_storage
    server.stop()


def test_engine_server_query_and_status(engine_server):
    server, engine, storage = engine_server
    base = f"http://127.0.0.1:{server.port}"
    status, body = http("POST", f"{base}/queries.json", {"mult": 5})
    assert status == 200 and body == {"result": 15.0}
    status, body = http("GET", f"{base}/")
    assert body["status"] == "alive"
    assert body["engineId"] == "const"
    assert body["stats"]["requestCount"] == 1
    assert body["stats"]["avgServingSec"] > 0
    # malformed query -> 400
    assert http("POST", f"{base}/queries.json", {"wrong": 1})[0] == 400
    assert http("GET", f"{base}/nope")[0] == 404


def test_engine_server_reload_hot_swaps(engine_server):
    server, engine, storage = engine_server
    base = f"http://127.0.0.1:{server.port}"
    assert http("POST", f"{base}/queries.json", {"mult": 1})[1] == {"result": 3.0}
    # retrain with new params, then /reload (ref: CreateServer.scala:592)
    train_const(storage, ds_value=10.0, algo_value=10.0)  # model = 20
    status, body = http("GET", f"{base}/reload")
    assert status == 200
    assert http("POST", f"{base}/queries.json", {"mult": 1})[1] == {"result": 20.0}


def test_micro_batched_concurrent_queries(engine_server):
    """Concurrent requests coalesce through Deployment.query_batch and
    every waiter gets ITS result; a malformed query in a batch 400s
    alone instead of failing its batchmates."""
    import threading

    server, engine, storage = engine_server
    base = f"http://127.0.0.1:{server.port}"
    payloads = [{"mult": m} for m in range(1, 9)] + [{"wrong": 1}]
    results = [None] * len(payloads)

    def fire(i):
        results[i] = http("POST", f"{base}/queries.json", payloads[i])

    threads = [threading.Thread(target=fire, args=(i,))
               for i in range(len(payloads))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i, m in enumerate(range(1, 9)):
        assert results[i] == (200, {"result": 3.0 * m}), results[i]
    assert results[-1][0] == 400
    # server still healthy afterwards
    assert http("POST", f"{base}/queries.json", {"mult": 2})[1] == {"result": 6.0}


def test_deployment_query_batch_matches_query(memory_storage):
    engine, instance = train_const(memory_storage)
    from predictionio_tpu.workflow.deploy import prepare_deploy

    dep = prepare_deploy(engine, instance, storage=memory_storage)
    payloads = [{"mult": m} for m in (2, 5, 7)]
    assert dep.query_batch(payloads) == [dep.query(p) for p in payloads]


def test_engine_server_requires_completed_instance(memory_storage):
    with pytest.raises(RuntimeError, match="No valid engine instance"):
        EngineServer(const_engine(), "never-trained", host="127.0.0.1", port=0,
                     storage=memory_storage)


def test_engine_server_stop_route(memory_storage):
    engine, _ = train_const(memory_storage)
    server = EngineServer(engine, "const", host="127.0.0.1", port=0,
                          storage=memory_storage).start()
    base = f"http://127.0.0.1:{server.port}"
    assert http("POST", f"{base}/stop")[1] == {"message": "stopping"}
    time.sleep(0.2)
    with pytest.raises(Exception):
        http("GET", f"{base}/", None)


def test_feedback_loop(memory_storage):
    """Query -> async predict event lands in the event store
    (ref: CreateServer.scala:488-550)."""
    app = memory_storage.apps().insert("fb-app")
    memory_storage.events().init(app.id)
    key = AccessKey.generate(app.id)
    memory_storage.access_keys().insert(key)
    event_srv = EventServer(storage=memory_storage, host="127.0.0.1", port=0).start()
    engine, _ = train_const(memory_storage)
    engine_srv = EngineServer(
        engine, "const", host="127.0.0.1", port=0, storage=memory_storage,
        feedback_url=f"http://127.0.0.1:{event_srv.port}",
        feedback_access_key=key.key,
    ).start()
    try:
        http("POST", f"http://127.0.0.1:{engine_srv.port}/queries.json", {"mult": 2})
        deadline = time.time() + 5
        events = []
        while time.time() < deadline:
            events = memory_storage.events().find(app.id, event_names=["predict"])
            if events:
                break
            time.sleep(0.05)
        assert events, "feedback predict event never arrived"
        props = events[0].properties
        assert props.get("query", dict) == {"mult": 2}
        prediction = props.get("prediction", dict)
        assert prediction["result"] == 6.0
        # prId joins the event back to the served prediction
        assert events[0].pr_id == prediction["prId"]
        assert events[0].entity_type == "pio_pr"
    finally:
        engine_srv.stop()
        event_srv.stop()


def test_event_server_review_regressions(event_server):
    """400s (not 500s) for bad eventTime / bad limit; target filters work;
    Basic-auth credentials accepted."""
    server, app, key = event_server
    base = f"http://127.0.0.1:{server.port}/events.json"
    auth = f"accessKey={key.key}"
    status, body = http("POST", f"{base}?{auth}", {
        "event": "rate", "entityType": "user", "entityId": "u1",
        "eventTime": "not-a-date"})
    assert status == 400
    assert http("GET", f"{base}?{auth}&limit=abc")[0] == 400
    # target entity filters
    for iid in ("i1", "i2"):
        http("POST", f"{base}?{auth}", {"event": "rate", "entityType": "user",
             "entityId": "u1", "targetEntityType": "item", "targetEntityId": iid})
    status, body = http("GET", f"{base}?{auth}&targetEntityType=item&targetEntityId=i2")
    assert status == 200 and len(body) == 1 and body[0]["targetEntityId"] == "i2"
    # Basic auth: key as username (ref: withAccessKey credentials path)
    import base64 as b64
    req = urllib.request.Request(
        f"{base}", method="GET",
        headers={"Authorization": "Basic " + b64.b64encode(f"{key.key}:".encode()).decode()},
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        assert resp.status == 200


def test_deploy_warmup_first_query_is_warm(memory_storage):
    """Deploy-time warm-up (SURVEY.md §7.5 hard part #2): the first live
    query after deploy must not pay XLA compile — it has to land within
    2x the warm p50 (plus a small timer-noise floor)."""
    import numpy as np

    from predictionio_tpu.core import Engine, EngineParams, FirstServing
    from predictionio_tpu.models.als import ALSAlgorithm, ALSParams
    from predictionio_tpu.templates.recommendation import (
        RecoDataSource,
        RecoDataSourceParams,
        RecoPreparator,
    )
    from predictionio_tpu.data.event import Event

    app = memory_storage.apps().insert("warm")
    memory_storage.events().init(app.id)
    rng = np.random.default_rng(0)
    events = [
        Event(event="rate", entity_type="user", entity_id=f"u{rng.integers(20)}",
              target_entity_type="item", target_entity_id=f"i{rng.integers(12)}",
              properties={"rating": float(1 + k % 5)})
        for k in range(200)
    ]
    memory_storage.events().insert_batch(events, app.id)

    engine = Engine(RecoDataSource, RecoPreparator, {"als": ALSAlgorithm},
                    FirstServing)
    ep = EngineParams(
        data_source_params=("", RecoDataSourceParams(app_name="warm")),
        preparator_params=("", None),
        algorithm_params_list=[("als", ALSParams(rank=8, num_iterations=2,
                                                 block_size=16))],
        serving_params=("", None),
    )
    run_train(engine, ep, engine_id="warmals", storage=memory_storage)

    server = EngineServer(
        engine, "warmals", host="127.0.0.1", port=0, storage=memory_storage,
        micro_batch=False,
    ).start()
    try:
        query = {"user": "u1", "num": 10}
        t0 = time.perf_counter()
        first = server.query(query)
        first_sec = time.perf_counter() - t0
        assert first["itemScores"]
        laps = []
        for _ in range(20):
            t0 = time.perf_counter()
            server.query(query)
            laps.append(time.perf_counter() - t0)
        warm_p50 = sorted(laps)[len(laps) // 2]
        assert first_sec <= max(2 * warm_p50, warm_p50 + 0.15), (
            f"first query {first_sec:.3f}s vs warm p50 {warm_p50:.4f}s — "
            "deploy warm-up did not pre-compile the serve bucket"
        )
    finally:
        server.stop()


def test_engine_server_html_landing_page(engine_server):
    """Browsers get the operator landing page at / (ref:
    CreateServer.scala:433-459 + twirl index template); programmatic
    clients keep the JSON status contract."""
    server, engine, storage = engine_server
    base = f"http://127.0.0.1:{server.port}"
    req = urllib.request.Request(base + "/", headers={"Accept": "text/html"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        assert resp.headers["Content-Type"].startswith("text/html")
        html = resp.read().decode()
    assert "<html>" in html and "const" in html
    assert "Requests served" in html
    # default Accept still returns JSON
    status, body = http("GET", f"{base}/")
    assert status == 200 and body["status"] == "alive"


def test_log_url_error_forwarding(memory_storage):
    """--log-url: serve errors POST to the remote log endpoint (ref:
    CreateServer.scala:413-424); a failing query still answers 500."""
    received = []

    from predictionio_tpu.serving.http import HTTPServerBase, JSONRequestHandler

    class _SinkHandler(JSONRequestHandler):
        def do_POST(self):
            received.append(json.loads(self._read_body()))
            self._send(200, {"ok": True})

    class _Sink(HTTPServerBase):
        pass

    sink = _Sink("127.0.0.1", 0, _SinkHandler).start()
    engine, _ = train_const(memory_storage)
    server = EngineServer(
        engine, "const", host="127.0.0.1", port=0, storage=memory_storage,
        log_url=f"http://127.0.0.1:{sink.port}/log", micro_batch=False,
    ).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        # ConstAlgo.predict: model * query["mult"] — a string multiplies
        # a float into TypeError deep in predict -> 400 bad-query path;
        # use a payload that raises beyond (KeyError/TypeError/ValueError)
        # via query() machinery: shut down the deployment's serving
        server.deployment.serving = None  # force an AttributeError
        status, body = http("POST", f"{base}/queries.json", {"mult": 2})
        assert status == 500
        deadline = time.perf_counter() + 5
        while not received and time.perf_counter() < deadline:
            time.sleep(0.05)
        assert received, "no remote log POST arrived"
        assert received[0]["level"] == "ERROR"
        assert "query failed" in received[0]["message"]
        assert received[0]["engineId"] == "const"
    finally:
        server.stop()
        sink.stop()


# ---------------------------------------------------------------------------
# POST /batch/events.json (ref: EventAPI.scala:252) — array in,
# per-event statuses out, through BOTH lanes: the native fast path
# (eventlog storage, raw bytes to C++) and the per-row Python fallback
# (memory storage / whitelisted keys).
# ---------------------------------------------------------------------------

BATCH_ROWS = [
    {"event": "rate", "entityType": "user", "entityId": "u1",
     "targetEntityType": "item", "targetEntityId": "i1",
     "properties": {"rating": 5.0},
     "eventTime": "2026-01-01T00:00:00.000Z"},
    {"event": "", "entityType": "user", "entityId": "u2"},      # invalid
    {"event": "view", "entityType": "user", "entityId": "u3",
     "eventTime": "2026-01-01T01:00:00.000Z"},
]


def _assert_batch_contract(base, key, storage, app_id):
    status, results = http("POST", f"{base}/batch/events.json?accessKey={key.key}",
                           BATCH_ROWS)
    assert status == 200 and len(results) == 3
    assert results[0]["status"] == 201 and results[0]["eventId"]
    assert results[1]["status"] == 400 and "empty" in results[1]["message"]
    assert results[2]["status"] == 201
    # one bad event never fails its batchmates
    stored = storage.events().find(app_id)
    assert sorted(e.entity_id for e in stored
                  if e.event in ("rate", "view")) == ["u1", "u3"]
    got = storage.events().get(results[0]["eventId"], app_id)
    assert got is not None and got.properties.to_dict() == {"rating": 5.0}
    # stats counted both statuses
    s, report = http("GET", f"{base}/stats.json?accessKey={key.key}")
    counts = {(c["status"], c["event"]): c["count"]
              for b in report["buckets"] for c in b["counts"]}
    assert counts.get((201, "rate")) == 1
    assert counts.get((400, "")) == 1


def test_batch_events_python_fallback_lane(event_server):
    """Memory storage has no native lane: the per-row Python path."""
    server, app, key = event_server
    _assert_batch_contract(f"http://127.0.0.1:{server.port}", key,
                           server.core.storage, app.id)


def test_batch_events_native_lane(tmp_path):
    """Eventlog storage: the raw body goes straight to the native
    encoder — same wire contract as the Python path."""
    from tests.test_storage import make_storage

    storage = make_storage("eventlog", tmp_path)
    app = storage.apps().insert("batch-app")
    storage.events().init(app.id)
    key = AccessKey.generate(app.id)
    storage.access_keys().insert(key)
    server = EventServer(storage=storage, host="127.0.0.1", port=0).start()
    try:
        _assert_batch_contract(f"http://127.0.0.1:{server.port}", key,
                               storage, app.id)
    finally:
        server.stop()
        storage.events().close()


def test_batch_events_whitelist_uses_python_path(tmp_path):
    """A key with an event whitelist needs per-event allow/deny: the
    native lane must NOT engage, and disallowed events 403 per-row."""
    from tests.test_storage import make_storage

    storage = make_storage("eventlog", tmp_path)
    app = storage.apps().insert("wl-app")
    storage.events().init(app.id)
    key = AccessKey.generate(app.id, events=["rate"])
    storage.access_keys().insert(key)
    server = EventServer(storage=storage, host="127.0.0.1", port=0).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        status, results = http(
            "POST", f"{base}/batch/events.json?accessKey={key.key}",
            BATCH_ROWS)
        assert status == 200
        assert results[0]["status"] == 201
        assert results[1]["status"] == 400
        assert results[2]["status"] == 403  # "view" not whitelisted
        assert [e.event for e in storage.events().find(app.id)] == ["rate"]
    finally:
        server.stop()
        storage.events().close()


def test_batch_events_malformed_body(event_server):
    server, app, key = event_server
    base = f"http://127.0.0.1:{server.port}"
    status, body = http("POST", f"{base}/batch/events.json?accessKey={key.key}",
                        {"not": "an array"})
    assert status == 400


def test_saturating_load_batches_form_and_p99_bounded(memory_storage):
    """VERDICT r3 item 6: 32 concurrent keep-alive connections through
    /queries.json — no errors, bounded tail latency, and the
    MicroBatcher histogram (in / status JSON) proves batches > 1
    actually form under load."""
    import threading

    class SlowAlgo(ConstAlgo):
        # ~1.5ms per DISPATCH (not per query): enough device-busy time
        # for queues to form, with per-query cost amortized by batching
        def predict(self, model, query):
            time.sleep(0.0015)
            return super().predict(model, query)

        def batch_predict(self, model, queries):
            time.sleep(0.0015)
            return [(i, super(SlowAlgo, self).predict(model, q))
                    for i, q in queries]

    engine = Engine(ConstDataSource, IdentityPreparator,
                    {"slow": SlowAlgo}, FirstServing)
    ep = EngineParams(
        data_source_params=("", ConstParams(value=1.0)),
        preparator_params=("", None),
        algorithm_params_list=[("slow", ConstParams(value=2.0))],
        serving_params=("", None),
    )
    run_train(engine, ep, engine_id="slow", storage=memory_storage)
    server = EngineServer(engine, "slow", host="127.0.0.1", port=0,
                          storage=memory_storage).start()
    try:
        import http.client as _hc

        base_port = server.port
        n_threads, per_thread = 32, 12
        errs, lat = [], [[] for _ in range(n_threads)]

        def worker(tid):
            try:
                c = _hc.HTTPConnection("127.0.0.1", base_port, timeout=30)
                for j in range(per_thread):
                    t0 = time.perf_counter()
                    c.request("POST", "/queries.json",
                              body=json.dumps({"mult": 2}),
                              headers={"Content-Type": "application/json"})
                    r = c.getresponse()
                    body = r.read()
                    assert r.status == 200, body
                    assert json.loads(body) == {"result": 6.0}
                    lat[tid].append(time.perf_counter() - t0)
                c.close()
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs[0]
        flat = sorted(x for ls in lat for x in ls)
        p99 = flat[int(len(flat) * 0.99)]
        # generous absolute bound for CI boxes; the REAL perf claim is
        # measured by bench.py on the bench host (p99 < 25 ms gate)
        assert p99 < 2.0, f"p99 {p99 * 1e3:.1f} ms under 32-conn load"

        # the histogram is served in the status JSON and shows real
        # batching: without it, 384 queries x 1.5 ms serialized would
        # need ~0.58 s of pure dispatch time; with batching far less
        status, body = http("GET", f"http://127.0.0.1:{base_port}/")
        assert status == 200
        hist = body["batcher"]["batchSizeHistogram"]
        assert sum(int(k) * v for k, v in hist.items()) == 384
        batched = sum(v for k, v in hist.items() if int(k) > 1)
        assert batched > 0, hist

        # the queue-wait vs dispatch split (VERDICT r4 item 5): every
        # answered request leaves a (wait, dispatch) pair whose parts
        # are sane — dispatch covers the ~1.5ms sleep, and the recorded
        # count covers the full offered load
        splits = server._batcher.recent_splits(384)
        assert len(splits) == 384
        waits = sorted(s[0] for s in splits)
        disp = sorted(s[1] for s in splits)
        assert disp[len(disp) // 2] >= 0.0014   # the dispatch sleep
        assert all(w >= 0 for w in waits)
        # under 32 conns vs ~1.5ms dispatches, SOME queueing must show
        assert waits[-1] > 0.0005
    finally:
        server.stop()


def test_batch_events_native_lane_over_rest_tier(tmp_path):
    """The native lane END-TO-END across the distributed tier: event
    server -> rest storage client -> storage server -> native eventlog
    encoder — the raw JSON array bytes cross both hosts with zero
    per-row Python anywhere. Non-native backends answer "unsupported"
    and the event server falls back per-row."""
    from tests.test_sharded_storage import _client
    from tests.test_storage import make_storage
    from predictionio_tpu.serving.storage_server import StorageServer

    backend = make_storage("eventlog", tmp_path)
    ss = StorageServer(storage=backend, host="127.0.0.1", port=0).start()
    try:
        client = _client([ss.port])
        app = client.apps().insert("wire-app")
        client.events().init(app.id)
        key = AccessKey.generate(app.id)
        client.access_keys().insert(key)
        es = EventServer(storage=client, host="127.0.0.1", port=0).start()
        try:
            _assert_batch_contract(f"http://127.0.0.1:{es.port}", key,
                                   client, app.id)
            # the rows really landed on the storage server's backend
            stored = backend.events().find(app.id)
            assert sorted(e.entity_id for e in stored
                          if e.event in ("rate", "view")) == ["u1", "u3"]
        finally:
            es.stop()
    finally:
        ss.stop()
        backend.events().close()


def test_rest_insert_json_unsupported_backend_falls_back(memory_storage):
    """A storage server on a backend with no native lane answers
    "unsupported"; the rest client raises JsonRowsUnsupported and the
    event server batch route still works via the per-row path."""
    from tests.test_sharded_storage import _client
    from predictionio_tpu.data.backends.eventlog import JsonRowsUnsupported
    from predictionio_tpu.serving.storage_server import StorageServer

    ss = StorageServer(storage=memory_storage, host="127.0.0.1",
                       port=0).start()
    try:
        client = _client([ss.port])
        app = client.apps().insert("fb-app")
        client.events().init(app.id)
        with pytest.raises(JsonRowsUnsupported):
            client.events().insert_json_batch(
                json.dumps(BATCH_ROWS[:1]).encode(), app.id)
        key = AccessKey.generate(app.id)
        client.access_keys().insert(key)
        es = EventServer(storage=client, host="127.0.0.1", port=0).start()
        try:
            _assert_batch_contract(f"http://127.0.0.1:{es.port}", key,
                                   client, app.id)
        finally:
            es.stop()
    finally:
        ss.stop()


def test_record_splits_skips_abandoned_requests():
    """An abandoned submitter (timeout raced the dispatch) must NOT
    leak its give-up-sized queue wait / skipped-work dispatch time into
    the (queue_wait, dispatch) splits the bench percentiles read — it
    is counted separately instead (advisor finding, r6)."""
    import threading as _th

    from predictionio_tpu.serving.engine_server import MicroBatcher

    release = _th.Event()

    def run_one(payload):
        release.wait(5.0)  # hold the dispatch until the submitter quits
        return payload

    def run_batch(payloads):
        release.wait(5.0)
        return list(payloads)

    b = MicroBatcher(run_batch, run_one)
    try:
        with pytest.raises(TimeoutError):
            b.submit("q1", timeout=0.05)   # abandons mid-dispatch
        release.set()
        deadline = time.time() + 5.0
        while b.histogram()["abandonedRequests"] < 1:
            assert time.time() < deadline, "abandoned request never counted"
            time.sleep(0.01)
        assert b.recent_splits(10) == []   # nothing skewed the splits
        # a live request afterwards records exactly one split
        assert b.submit("q2", timeout=5.0) == "q2"
        splits = b.recent_splits(10)
        assert len(splits) == 1
        wait_sec, dispatch_sec = splits[0]
        assert 0.0 <= wait_sec < 1.0 and 0.0 <= dispatch_sec < 1.0
        assert b.histogram()["abandonedRequests"] == 1
    finally:
        release.set()
        b.stop()
