"""Regression sentinel (obs/anomaly.py): the MAD/CUSUM detector pins
(pure-function verdicts over fixed rings), journal attribution
windowing, the sentinel lifecycle over a fake timeline, and the
snapshot-listener isolation the cadence wiring rides on."""

import pytest

from predictionio_tpu.obs import anomaly, journal


def pts(vals, t0=1000.0, dt=15.0):
    return [(t0 + i * dt, float(v)) for i, v in enumerate(vals)]


#: 24 baseline points: median 10, alternating +/-0.2 wiggle so the MAD
#: (and the robust sigma) is nonzero and the z pins are honest
BASE = [10.0 + (0.2 if i % 2 else -0.2) for i in range(24)]

UP = {"direction": "up", "deadband": 0.10, "abs_deadband": 1.0}
DOWN = {"direction": "down", "deadband": 0.10, "abs_deadband": 1.0}


def detect(vals, cfg=UP, z=3.0, h=6.0, min_samples=12):
    return anomaly.detect(pts(vals), cfg=cfg, z_threshold=z, cusum_h=h,
                          min_samples=min_samples)


class TestDetectorPins:
    """The deterministic core: same ring, same verdict, pinned numbers
    (baseline median 10, MAD-sigma 0.29652)."""

    def test_step_up(self):
        v = detect(BASE + [10.0] * 6 + [15.0] * 6)
        assert v["mode"] == "step"
        assert v["direction"] == "up"
        assert v["baseline"] == 10.0
        assert v["sigma"] == pytest.approx(0.29652)
        assert v["recent"] == 15.0
        assert v["z"] == pytest.approx(16.86)
        # onset = the first sample of the trailing out-of-band run:
        # index 30 of a 15 s cadence starting at t=1000
        assert v["onset_ts"] == 1450.0

    def test_step_down(self):
        v = detect(BASE + [10.0] * 6 + [5.0] * 6, cfg=DOWN)
        assert v["mode"] == "step"
        assert v["direction"] == "down"
        assert v["z"] == pytest.approx(-16.86)

    def test_slow_drift_trips_cusum_below_z_threshold(self):
        # +0.05/sample ramp: recent median only 1.5 sigma out (far
        # under the z=10 gate) but the one-sided CUSUM accumulates
        drift = [10.0 + 0.05 * k for k in range(12)]
        v = anomaly.detect(
            pts(BASE + drift),
            cfg={"direction": "up", "deadband": 0.02,
                 "abs_deadband": 0.1},
            z_threshold=10.0, cusum_h=6.0, min_samples=12)
        assert v["mode"] == "drift"
        assert v["z"] == pytest.approx(1.52)
        assert v["cusum"] == pytest.approx(6.12)
        assert v["onset_ts"] == 1435.0

    def test_deadband_holds_through_small_shift(self):
        # +0.1 on a baseline of 10 is inside the 10% band: many sigmas
        # (sigma 0.297) but not an incident
        assert detect(BASE + [10.1] * 12) is None

    def test_direction_config_gates_the_alarm(self):
        vals = BASE + [10.0] * 6 + [15.0] * 6
        assert detect(vals, cfg=DOWN) is None  # a rise is fine for p99-down
        assert detect(vals, cfg=UP) is not None
        both = {"direction": "both", "deadband": 0.10, "abs_deadband": 1.0}
        assert detect(vals, cfg=both) is not None

    def test_not_enough_history_is_silent(self):
        assert detect([10.0] * 10) is None
        assert detect([10.0] * 13) is None  # baseline ok, scan too thin

    def test_one_outlier_does_not_trip_drift(self):
        # Z_CLIP bounds a single wild point's CUSUM contribution
        vals = BASE + [10.0] * 11 + [500.0]
        v = detect(vals, z=100.0, h=10.0)
        assert v is None

    def test_flat_baseline_sigma_floor(self):
        # MAD 0 must not make every wiggle infinite sigmas
        v = anomaly.detect(pts([10.0] * 24 + [15.0] * 6),
                           cfg=UP, z_threshold=3.0, cusum_h=6.0,
                           min_samples=12)
        assert v is not None
        assert v["sigma"] == pytest.approx(0.01)  # 1e-3 * |median|


class TestSeriesConfig:
    def test_longest_dotted_prefix_wins(self):
        assert anomaly.series_config(
            "quality.rmse_drift.eng")["direction"] == "up"
        assert anomaly.series_config(
            "quality.recall.eng")["direction"] == "down"
        assert anomaly.series_config("serve_p99_ms.e")["direction"] == "up"
        assert (anomaly.series_config("never_configured")
                is anomaly._DEFAULT_CFG)


class TestAttribution:
    def test_nearest_preceding_event_wins(self, monkeypatch):
        monkeypatch.setenv("PIO_ANOMALY_WINDOW_SEC", "30")
        events = [
            {"ts": 960.0, "kind": "patch"},     # outside the window
            {"ts": 985.0, "kind": "reload", "instance": "i-2"},
            {"ts": 995.0, "kind": "breaker", "target": "t"},  # closest
        ]
        cause = anomaly.attribute(1000.0, events)
        assert cause["kind"] == "breaker"
        assert cause["gap_sec"] == pytest.approx(5.0)

    def test_event_after_onset_loses_to_preceding(self, monkeypatch):
        monkeypatch.setenv("PIO_ANOMALY_WINDOW_SEC", "30")
        events = [
            {"ts": 980.0, "kind": "reload"},
            {"ts": 1001.0, "kind": "swap"},  # nearer but AFTER onset
        ]
        assert anomaly.attribute(1000.0, events)["kind"] == "reload"

    def test_event_after_onset_can_still_name_it(self, monkeypatch):
        monkeypatch.setenv("PIO_ANOMALY_WINDOW_SEC", "30")
        events = [{"ts": 1003.0, "kind": "reload"}]
        cause = anomaly.attribute(1000.0, events)
        assert cause["kind"] == "reload"
        assert cause["gap_sec"] == pytest.approx(-3.0)

    def test_nothing_in_window_is_unattributed(self, monkeypatch):
        monkeypatch.setenv("PIO_ANOMALY_WINDOW_SEC", "30")
        assert anomaly.attribute(
            1000.0, [{"ts": 900.0, "kind": "reload"}]) is None

    def test_sentinel_events_never_explain_an_anomaly(self, monkeypatch):
        monkeypatch.setenv("PIO_ANOMALY_WINDOW_SEC", "30")
        events = [{"ts": 999.0, "kind": "anomaly", "series": "x"},
                  {"ts": 998.0, "kind": "anomaly_resolved"}]
        assert anomaly.attribute(1000.0, events) is None


@pytest.fixture()
def fake_timeline(monkeypatch):
    """A fresh Timeline installed as the process singleton, plus a
    helper to fill one series ring directly."""
    import collections

    from predictionio_tpu.obs import timeline

    tl = timeline.Timeline()
    monkeypatch.setattr(timeline, "TIMELINE", tl)

    def fill(name, vals, t0=1000.0, dt=15.0):
        ring = tl._series.setdefault(
            name, collections.deque(maxlen=360))
        ring.clear()
        for i, v in enumerate(vals):
            ring.append((t0 + i * dt, float(v)))

    tl.fill = fill
    return tl


class TestSentinelLifecycle:
    SERIES = "serve_p99_ms.eng"

    def test_scan_detects_attributes_and_resolves(self, fake_timeline,
                                                  monkeypatch):
        monkeypatch.setenv("PIO_ANOMALY_WINDOW_SEC", "60")
        fake_timeline.fill(self.SERIES, BASE + [10.0] * 6 + [15.0] * 6)
        # the causal event lands just before the onset (index 30 ->
        # ts 1450)
        journal.JOURNAL.emit("reload", instance="i-9")
        journal.JOURNAL._ring[-1]["ts"] = 1445.0
        report = anomaly.SENTINEL.scan(now=1540.0)
        assert self.SERIES in report["active"]
        verdict = report["active"][self.SERIES]
        assert verdict["mode"] == "step"
        assert verdict["since"] == 1540.0
        assert verdict["cause"]["kind"] == "reload"
        assert verdict["cause"]["instance"] == "i-9"
        assert verdict["cause"]["gap_sec"] == pytest.approx(5.0)
        assert anomaly.SENTINEL.any_active()
        assert anomaly._ACTIVE.labels(self.SERIES).value == 1.0
        onsets = journal.JOURNAL.recent(kind="anomaly")
        assert len(onsets) == 1
        assert onsets[0]["series"] == self.SERIES
        assert onsets[0]["cause_kind"] == "reload"

        # a second scan with the shift still in the ring: the episode
        # CONTINUES (no second journal event, onset/cause sticky)
        report = anomaly.SENTINEL.scan(now=1555.0)
        assert report["active"][self.SERIES]["since"] == 1540.0
        assert report["active"][self.SERIES]["cause"]["kind"] == "reload"
        assert len(journal.JOURNAL.recent(kind="anomaly")) == 1

        # recovery: the ring turns over to flat again -> resolved
        fake_timeline.fill(self.SERIES, BASE + [10.0] * 12)
        report = anomaly.SENTINEL.scan(now=1600.0)
        assert report["active"] == {}
        assert not anomaly.SENTINEL.any_active()
        assert anomaly._ACTIVE.labels(self.SERIES).value == 0.0
        resolved = journal.JOURNAL.recent(kind="anomaly_resolved")
        assert len(resolved) == 1
        assert resolved[0]["duration_sec"] == pytest.approx(60.0)
        episode = report["recent_resolved"][-1]
        assert episode["series"] == self.SERIES
        assert episode["resolved_ts"] == 1600.0
        assert episode["duration_sec"] == pytest.approx(60.0)

    def test_unattributed_anomaly_has_no_cause(self, fake_timeline,
                                               monkeypatch):
        monkeypatch.setenv("PIO_ANOMALY_WINDOW_SEC", "30")
        fake_timeline.fill(self.SERIES, BASE + [10.0] * 6 + [15.0] * 6)
        report = anomaly.SENTINEL.scan(now=1540.0)
        assert "cause" not in report["active"][self.SERIES]
        assert journal.JOURNAL.recent(kind="anomaly")[0].get(
            "cause_kind") is None

    def test_report_shape(self):
        report = anomaly.SENTINEL.report()
        assert set(report) == {"window_sec", "active", "recent_resolved",
                               "scan_ms"}
        assert report["active"] == {}


class TestSnapshotListenerIsolation:
    """One broken cadence listener must neither starve the others nor
    fail silently (pio_snapshot_listener_errors_total{listener})."""

    def test_broken_listener_is_counted_and_isolated(self, monkeypatch):
        from predictionio_tpu.obs import flight

        ran = []

        def broken():
            raise RuntimeError("boom")

        def healthy():
            ran.append(True)

        monkeypatch.setattr(flight, "_snapshot_listeners",
                            [("broken_fixture", broken),
                             ("healthy_fixture", healthy)])
        errors = flight._LISTENER_ERRORS_TOTAL.labels("broken_fixture")
        base = errors.value
        # interval 0: every sealed record takes a snapshot, which is
        # the cadence the listeners ride
        recorder = flight.FlightRecorder(snapshot_interval=0.0)
        key = recorder.begin("0" * 32, "test", "GET", "/x")
        recorder.finish(key, 200)
        assert ran == [True]  # the healthy listener still ran
        assert errors.value == base + 1

    def test_add_snapshot_listener_names_and_dedupes(self, monkeypatch):
        from predictionio_tpu.obs import flight

        listeners = []
        monkeypatch.setattr(flight, "_snapshot_listeners", listeners)

        def fn():
            pass

        flight.add_snapshot_listener(fn, name="mine")
        flight.add_snapshot_listener(fn, name="mine")  # idempotent
        assert listeners == [("mine", fn)]
        flight.add_snapshot_listener(lambda: None)
        assert listeners[-1][0]  # anonymous fallback still labelled
