"""ops/pallas kernels under the interpreter (JAX_PLATFORMS=cpu):
fwd/bwd equivalence against the XLA reference paths, the adagrad
update, and the selection/fallback machinery.

The contract these tests pin (ops/pallas/__init__.py): the XLA forms
in ops/twotower.py remain the numerical reference; a kernel may only
replace one if it agrees to <=1e-5 in f32 — including in-batch
duplicate users/items, zero-weight padding rows, and a RAGGED last
grid tile — and selection must fall back (never fail) everywhere a
kernel is ineligible.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from predictionio_tpu.ops import pallas as plk
from predictionio_tpu.ops.pallas.embed_update import pallas_rowwise_adagrad
from predictionio_tpu.ops.pallas.flash_ce import (
    make_flash_ce,
    pallas_blockwise_ce,
)
from predictionio_tpu.ops.twotower import (
    TwoTowerConfig,
    TwoTowerTrainer,
    _dense_softmax_ce,
    _make_blockwise_ce_vjp,
    _rowwise_adagrad,
)


def _batch(B, D, seed=9, n_users=60, n_items=40, n_pad=17,
           uniform_w=True):
    """Unit-norm towers + index vectors with many in-batch duplicates
    and a zero-weight padded tail — the full masking surface.
    ``uniform_w=False`` draws real-valued weights (the
    ``weight_by_rating`` path), exercising the w-asymmetric terms of
    the loss and backward that 0/1 weights cannot distinguish."""
    rng = np.random.default_rng(seed)
    u = rng.normal(size=(B, D)).astype(np.float32)
    v = rng.normal(size=(B, D)).astype(np.float32)
    u /= np.linalg.norm(u, axis=1, keepdims=True)
    v /= np.linalg.norm(v, axis=1, keepdims=True)
    u_idx = rng.integers(0, n_users, B).astype(np.int32)
    i_idx = rng.integers(0, n_items, B).astype(np.int32)
    w = (np.ones(B, np.float32) if uniform_w
         else (0.5 + 4.0 * rng.random(B)).astype(np.float32))
    if n_pad:
        w[-n_pad:] = 0.0
    return (jnp.asarray(u), jnp.asarray(v), jnp.asarray(u_idx),
            jnp.asarray(i_idx), jnp.asarray(w))


@pytest.mark.parametrize("cdt_name,uniform_w,l_rtol,g_rtol,g_atol", [
    ("float32", True, 1e-5, 1e-4, 1e-6),
    # weight_by_rating shape: real-valued weights exercise the
    # w-asymmetric loss/backward terms 0/1 weights cannot distinguish
    ("float32", False, 1e-5, 1e-4, 1e-6),
    # bf16 tile logits: same tolerance story as the XLA blockwise test
    # (quantization under different summation orders)
    ("bfloat16", True, 5e-3, 1e-1, 2e-3),
])
def test_flash_ce_matches_xla_paths(cdt_name, uniform_w, l_rtol, g_rtol,
                                    g_atol):
    """Loss AND grads of the Pallas flash-CE agree with the dense
    reference and the XLA blockwise VJP it replaces."""
    B, D, block = 256, 16, 64
    u, v, u_idx, i_idx, w = _batch(B, D, uniform_w=uniform_w)
    cdt = jnp.dtype(cdt_name)

    def dense(u_, v_):
        return _dense_softmax_ce(u_, v_, u_idx, i_idx, w, 0.07, cdt)

    xla = _make_blockwise_ce_vjp(u_idx, i_idx, w, 0.07, block, cdt, B)
    flash = make_flash_ce(u_idx, i_idx, w, 0.07, cdt, B,
                          interpret=True, block=block)

    ld, (gdu, gdv) = jax.value_and_grad(dense, argnums=(0, 1))(u, v)
    lx, (gxu, gxv) = jax.value_and_grad(xla, argnums=(0, 1))(u, v)
    lf, (gfu, gfv) = jax.value_and_grad(flash, argnums=(0, 1))(u, v)
    np.testing.assert_allclose(float(lf), float(ld), rtol=l_rtol)
    np.testing.assert_allclose(float(lf), float(lx), rtol=l_rtol)
    for got, ref in ((gfu, gdu), (gfv, gdv), (gfu, gxu), (gfv, gxv)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=g_rtol, atol=g_atol)


@pytest.mark.parametrize("B", [200, 130])
def test_flash_ce_ragged_last_tile(B):
    """B not divisible by the tile: the zero-pad path must stay exact
    vs the dense reference (which needs no padding)."""
    D, block = 16, 64
    u, v, u_idx, i_idx, w = _batch(B, D, seed=4, n_pad=9)

    def dense(u_, v_):
        return _dense_softmax_ce(u_, v_, u_idx, i_idx, w, 0.07,
                                 jnp.float32)

    flash = make_flash_ce(u_idx, i_idx, w, 0.07, jnp.float32, B,
                          interpret=True, block=block)
    ld, (gdu, gdv) = jax.value_and_grad(dense, argnums=(0, 1))(u, v)
    lf, (gfu, gfv) = jax.value_and_grad(flash, argnums=(0, 1))(u, v)
    np.testing.assert_allclose(float(lf), float(ld), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gfu), np.asarray(gdu),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gfv), np.asarray(gdv),
                               rtol=1e-4, atol=1e-6)
    assert gfu.shape == (B, D) and gfv.shape == (B, D)


def test_flash_ce_one_call_form_jits():
    """The convenience wrapper traces under jit (how the epoch scan
    uses it) and returns a finite f32 scalar."""
    B, D = 128, 8
    u, v, u_idx, i_idx, w = _batch(B, D, seed=2, n_pad=5)

    @jax.jit
    def f(u_, v_):
        return pallas_blockwise_ce(u_, v_, u_idx, i_idx, w, 0.07,
                                   jnp.float32, interpret=True, block=32)

    out = f(u, v)
    assert out.dtype == jnp.float32 and bool(jnp.isfinite(out))


@pytest.mark.parametrize("N,E,B,vocab", [
    (64, 24, 37, 64),    # ragged tile + non-128 row width
    (128, 16, 32, 128),  # aligned
    (50, 8, 24, 6),      # duplicate-heavy: every tile collides
])
def test_pallas_adagrad_matches_xla(N, E, B, vocab):
    """The fused embedding-update equals _rowwise_adagrad — table AND
    accumulator — including duplicate indices within and across tiles
    (read-after-full-add scale semantics)."""
    rng = np.random.default_rng(3)
    table = jnp.asarray(rng.normal(size=(N, E)).astype(np.float32))
    acc = jnp.asarray(np.abs(rng.normal(size=N)).astype(np.float32))
    idx = jnp.asarray(rng.integers(0, vocab, B).astype(np.int32))
    grad = jnp.asarray(rng.normal(size=(B, E)).astype(np.float32))

    t_ref, a_ref = _rowwise_adagrad(table, acc, idx, grad, 0.03)
    t_k, a_k = pallas_rowwise_adagrad(table, acc, idx, grad, 0.03,
                                      interpret=True)
    np.testing.assert_allclose(np.asarray(a_k), np.asarray(a_ref),
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(np.asarray(t_k), np.asarray(t_ref),
                               rtol=1e-5, atol=1e-6)


def test_pallas_adagrad_in_donated_jit():
    """The scan-body usage shape: jitted with donated buffers (the
    aliased in-place table update must compose with XLA donation)."""
    rng = np.random.default_rng(7)
    table = jnp.asarray(rng.normal(size=(40, 8)).astype(np.float32))
    acc = jnp.zeros((40,), jnp.float32)
    idx = jnp.asarray(rng.integers(0, 40, 16).astype(np.int32))
    grad = jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32))
    t_ref, a_ref = _rowwise_adagrad(table, acc, idx, grad, 0.05)

    f = jax.jit(lambda t, a: pallas_rowwise_adagrad(
        t, a, idx, grad, 0.05, interpret=True), donate_argnums=(0, 1))
    t_k, a_k = f(table, acc)
    np.testing.assert_allclose(np.asarray(t_k), np.asarray(t_ref),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(a_k), np.asarray(a_ref),
                               rtol=1e-6, atol=1e-7)


# -- selection / fallback ----------------------------------------------------


def _positives(n=700, n_users=80, n_items=50, seed=1):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, n_users, n), rng.integers(0, n_items, n),
            n_users, n_items)


def test_trainer_kernel_plan_defaults_off_on_cpu():
    """'auto' must NOT engage on a CPU backend (interpret mode is a
    test vehicle, not a production path) — existing CPU users keep the
    XLA forms untouched."""
    u, i, n_users, n_items = _positives()
    cfg = TwoTowerConfig(dim=8, epochs=1, batch_size=256, seed=3)
    tr = TwoTowerTrainer((u, i, None), n_users, n_items, cfg)
    assert tr.kernel_plan["flash_ce"] is False
    assert tr.kernel_plan["embed_update"] is False
    assert tr.kernel_plan["interpret"] is True  # cpu backend implies it


def test_trainer_kernel_plan_forced_on_engages_interpret():
    u, i, n_users, n_items = _positives()
    cfg = TwoTowerConfig(dim=8, epochs=1, batch_size=256, seed=3,
                         flash_ce_kernel="on", embed_update_kernel="on")
    tr = TwoTowerTrainer((u, i, None), n_users, n_items, cfg)
    assert tr.kernel_plan["flash_ce"] is True
    assert tr.kernel_plan["embed_update"] is True


def test_trainer_kernel_plan_env_overrides_config(monkeypatch):
    """The bench A/B switch: env beats the config flag."""
    monkeypatch.setenv("PIO_TT_FLASH_CE", "off")
    monkeypatch.setenv("PIO_TT_EMBED_UPDATE", "off")
    u, i, n_users, n_items = _positives()
    cfg = TwoTowerConfig(dim=8, epochs=1, batch_size=256, seed=3,
                         flash_ce_kernel="on", embed_update_kernel="on")
    tr = TwoTowerTrainer((u, i, None), n_users, n_items, cfg)
    assert tr.kernel_plan["flash_ce"] is False
    assert tr.kernel_plan["embed_update"] is False


def test_trainer_kernel_plan_ineligible_falls_back():
    """Multi-device mesh and small batches fall back with a reason —
    never an error (pallas_call does not partition under a mesh)."""
    from predictionio_tpu.parallel.mesh import create_mesh

    u, i, n_users, n_items = _positives()
    cfg = TwoTowerConfig(dim=4, epochs=1, batch_size=256, seed=3,
                         flash_ce_kernel="on", embed_update_kernel="on")
    tr = TwoTowerTrainer((u, i, None), n_users, n_items, cfg,
                         mesh=create_mesh({"data": 8}))
    assert tr.kernel_plan["flash_ce"] is False
    assert "mesh" in tr.kernel_plan["flash_ce_reason"]
    assert tr.kernel_plan["embed_update"] is False

    small = TwoTowerTrainer(
        (u, i, None), n_users, n_items,
        TwoTowerConfig(dim=4, epochs=1, batch_size=64, seed=3,
                       flash_ce_kernel="on"))
    assert small.kernel_plan["flash_ce"] is False
    assert "batch" in small.kernel_plan["flash_ce_reason"]


def test_trainer_kernels_end_to_end_match_xla():
    """A full trainer run with BOTH kernels engaged (interpret) tracks
    the XLA-path run epoch-for-epoch in f32 — the integration-level
    equivalence, scan + donation + adagrad included."""
    u, i, n_users, n_items = _positives(n=520, seed=5)
    base = dict(dim=8, epochs=2, batch_size=128, seed=7,
                learning_rate=1e-2, compute_dtype="float32")
    ref = TwoTowerTrainer((u, i, None), n_users, n_items,
                          TwoTowerConfig(**base))
    ker = TwoTowerTrainer((u, i, None), n_users, n_items,
                          TwoTowerConfig(**base, flash_ce_kernel="on",
                                         embed_update_kernel="on"))
    assert ker.kernel_plan["flash_ce"] and ker.kernel_plan["embed_update"]
    l_ref = ref.run()
    l_ker = ker.run()
    np.testing.assert_allclose(l_ker, l_ref, rtol=1e-4, atol=1e-5)
    e_ref = ref.embeddings(l_ref)
    e_ker = ker.embeddings(l_ker)
    np.testing.assert_allclose(e_ker.item_vecs, e_ref.item_vecs,
                               rtol=1e-3, atol=1e-4)


def test_probe_failure_disables_kernel(monkeypatch):
    """A smoke-probe crash must mean 'XLA fallback', never a failed
    train (the Mosaic-regression safety net)."""
    monkeypatch.setattr(plk, "_probe_cache", {})

    def boom():
        raise RuntimeError("mosaic said no")

    assert plk.probe("boom_kernel", boom) is False
    # memoized: the second call doesn't re-run the probe
    assert plk.probe("boom_kernel", boom) is False
    assert plk._probe_cache["boom_kernel"] is False


def test_flash_ce_weight_grad_raises_not_zero():
    """The documented nondiff contract: asking for d(loss)/d(weight)
    through the closed-over factory raises loudly instead of silently
    returning zeros (weighted-loss tuning hazard, ops/twotower.py
    _make_blockwise_ce_vjp docstring)."""
    B, D = 128, 8
    u, v, u_idx, i_idx, w = _batch(B, D, seed=8, n_pad=0)

    def loss_of_w(w_):
        fn = make_flash_ce(u_idx, i_idx, w_, 0.07, jnp.float32, B,
                           interpret=True, block=32)
        return fn(u, v)

    with pytest.raises(Exception):  # UnexpectedTracerError on jax 0.4.x
        jax.grad(loss_of_w)(w)


def test_pallas_import_failure_degrades_to_xla(monkeypatch):
    """An import-time break in jax.experimental.pallas (API churn)
    must leave every two-tower train on the XLA paths with the reason
    recorded — even with the kernels requested 'on' — not raise."""
    import predictionio_tpu.ops.twotower as tt

    monkeypatch.setattr(tt, "_pl_flash", None)
    monkeypatch.setattr(tt, "_pl_embed", None)
    monkeypatch.setattr(tt, "_PALLAS_IMPORT_ERROR",
                        "ImportError: no pallas today")
    u, i, n_users, n_items = _positives()
    cfg = TwoTowerConfig(dim=8, epochs=1, batch_size=128, seed=3,
                         flash_ce_kernel="on", embed_update_kernel="on")
    tr = tt.TwoTowerTrainer((u, i, None), n_users, n_items, cfg)
    assert tr.kernel_plan["flash_ce"] is False
    assert "unavailable" in tr.kernel_plan["flash_ce_reason"]
    assert tr.kernel_plan["embed_update"] is False
    assert tr.run() and len(tr.run()) == 1   # trains on the XLA path
