"""Native binning pass (raggedbin.cpp) must produce byte-identical
output to the numpy reference path, including truncation and sharding."""

import numpy as np
import pytest

from predictionio_tpu.ops import ragged

pytestmark = pytest.mark.skipif(
    not __import__("predictionio_tpu.native", fromlist=["native_available"]).native_available("raggedbin"),
    reason="C++ toolchain unavailable",
)


def _coo(n=500_000, n_groups=3_000, n_items=800, seed=0):
    rng = np.random.default_rng(seed)
    g = rng.integers(0, n_groups, size=n, dtype=np.int64)
    i = (rng.zipf(1.3, size=n) % n_items).astype(np.int64)
    v = rng.normal(size=n).astype(np.float32)
    return g, i, v


def _force(monkeypatch, native: bool):
    monkeypatch.setenv("PIO_NATIVE_RAGGED", "1" if native else "0")
    monkeypatch.setattr(ragged, "_NATIVE_MIN_NNZ", 0 if native else 10**18)


@pytest.mark.parametrize("max_len", [None, 64])
@pytest.mark.parametrize("n_shards", [1, 4])
def test_segmented_parity(monkeypatch, max_len, n_shards):
    g, i, v = _coo()
    n_groups = 3_000
    _force(monkeypatch, False)
    ref = ragged.build_segmented_groups(g, i, v, n_groups, max_len=max_len, n_shards=n_shards)
    _force(monkeypatch, True)
    got = ragged.build_segmented_groups(g, i, v, n_groups, max_len=max_len, n_shards=n_shards)
    np.testing.assert_array_equal(ref.idx, got.idx)
    np.testing.assert_array_equal(ref.val, got.val)
    np.testing.assert_array_equal(ref.mask, got.mask)
    np.testing.assert_array_equal(ref.seg, got.seg)
    np.testing.assert_array_equal(ref.counts, got.counts)


@pytest.mark.parametrize("max_len", [None, 32])
def test_padded_parity(monkeypatch, max_len):
    g, i, v = _coo(n=200_000, n_groups=1_000)
    _force(monkeypatch, False)
    ref = ragged.build_padded_groups(g, i, v, 1_000, max_len=max_len, group_multiple=8)
    _force(monkeypatch, True)
    got = ragged.build_padded_groups(g, i, v, 1_000, max_len=max_len, group_multiple=8)
    np.testing.assert_array_equal(ref.idx, got.idx)
    np.testing.assert_array_equal(ref.val, got.val)
    np.testing.assert_array_equal(ref.mask, got.mask)
    np.testing.assert_array_equal(ref.counts, got.counts)


def test_bad_group_id_raises(monkeypatch):
    _force(monkeypatch, True)
    g = np.array([0, 1, 99], dtype=np.int64)  # 99 >= n_groups
    i = np.zeros(3, dtype=np.int64)
    v = np.zeros(3, dtype=np.float32)
    with pytest.raises(ValueError):
        ragged.build_segmented_groups(g, i, v, n_groups=2)
