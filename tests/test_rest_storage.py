"""REST storage tier: DAO-level storage server + `rest` client backend.

The scale-out storage story (ref: the reference reaches HBase via client
RPC, Elasticsearch via the transport client, HDFS for model blobs —
SURVEY.md §2.5): N hosts configure a ``rest``-type storage source
pointing at one storage server and share one logical METADATA /
EVENTDATA / MODELDATA. Includes the cross-host proof: train in one
process, deploy from another, each with its own private localfs root,
sharing only the REST tiers.
"""

import datetime as _dt
import json
import os
import subprocess
import sys
import time

import pytest

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.metadata import (
    AccessKey,
    EngineInstance,
    EngineManifest,
    Model,
)
from predictionio_tpu.data.storage import UNSET, Storage, StorageError
from predictionio_tpu.serving.storage_server import StorageServer

UTC = _dt.timezone.utc
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _client_env(port: int, auth_key=None) -> dict:
    env = {
        "PIO_STORAGE_SOURCES_CENTRAL_TYPE": "rest",
        "PIO_STORAGE_SOURCES_CENTRAL_HOSTS": "127.0.0.1",
        "PIO_STORAGE_SOURCES_CENTRAL_PORTS": str(port),
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "meta",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "CENTRAL",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "events",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "CENTRAL",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "models",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "CENTRAL",
    }
    if auth_key:
        env["PIO_STORAGE_SOURCES_CENTRAL_AUTH_KEY"] = auth_key
    return env


def _client_storage(port: int, auth_key=None) -> Storage:
    return Storage.from_env(_client_env(port, auth_key))


@pytest.fixture()
def rest_storage(memory_storage):
    """(server over the in-memory storage, rest-client Storage)."""
    server = StorageServer(storage=memory_storage, host="127.0.0.1", port=0).start()
    try:
        yield memory_storage, _client_storage(server.port)
    finally:
        server.stop()


def _event(name="rate", eid="u1", tid=None, t=None, props=None):
    return Event(
        event=name,
        entity_type="user",
        entity_id=eid,
        target_entity_type="item" if tid else None,
        target_entity_id=tid,
        properties=props or {},
        event_time=t or _dt.datetime(2026, 1, 1, tzinfo=UTC),
    )


def test_event_roundtrip_and_filters(rest_storage):
    _, client = rest_storage
    store = client.events()
    store.init(1)
    t0 = _dt.datetime(2026, 1, 1, tzinfo=UTC)
    ids = store.insert_batch(
        [
            _event("rate", "u1", "i1", t0, {"rating": 4.5}),
            _event("buy", "u1", "i2", t0 + _dt.timedelta(hours=1)),
            _event("$set", "u2", None, t0 + _dt.timedelta(hours=2), {"a": 1}),
        ],
        1,
    )
    assert len(ids) == 3

    got = store.get(ids[0], 1)
    assert got.event == "rate"
    assert got.properties.get("rating") == 4.5
    assert got.event_time == t0

    assert len(store.find(1)) == 3
    assert [e.event for e in store.find(1, event_names=["buy"])] == ["buy"]
    # half-open [start, until) window over the wire
    win = store.find(1, start_time=t0, until_time=t0 + _dt.timedelta(hours=1))
    assert [e.event for e in win] == ["rate"]
    # tri-state target filter: None means "no target", UNSET means "any"
    assert len(store.find(1, target_entity_type=None)) == 1
    assert len(store.find(1, target_entity_type="item")) == 2
    assert store.find(1, target_entity_type=UNSET) == store.find(1)
    newest = store.find(1, limit=1, reversed=True)
    assert newest[0].event == "$set"

    assert store.delete(ids[1], 1) is True
    assert store.delete(ids[1], 1) is False
    assert len(store.find(1)) == 2

    # the derived aggregate_properties runs client-side over REST find
    props = store.aggregate_properties(1, "user")
    assert props["u2"].get("a") == 1


def test_event_errors_propagate(rest_storage):
    _, client = rest_storage
    with pytest.raises(StorageError):
        client.events().find(99)  # un-init()ed app table


def test_metadata_repos(rest_storage):
    _, client = rest_storage
    app = client.apps().insert("restapp", "desc")
    assert app.id >= 1
    assert client.apps().get_by_name("restapp").description == "desc"
    with pytest.raises(StorageError):
        client.apps().insert("restapp")  # duplicate name propagates

    key = client.access_keys().insert(AccessKey.generate(app.id, ["rate"]))
    assert client.access_keys().get(key).events == ["rate"]
    assert [k.key for k in client.access_keys().get_by_app_id(app.id)] == [key]

    ch = client.channels().insert("live", app.id)
    assert client.channels().get_by_app_id(app.id)[0].name == "live"
    with pytest.raises(StorageError):
        client.channels().insert("bad name!", app.id)

    manifest = EngineManifest(id="e1", version="1", name="engine one")
    client.engine_manifests().insert(manifest)
    assert client.engine_manifests().get("e1", "1").name == "engine one"
    assert client.engine_manifests().get("e1", "2") is None


def test_engine_instances_over_rest(rest_storage):
    _, client = rest_storage
    repo = client.engine_instances()
    t = _dt.datetime(2026, 1, 1, tzinfo=UTC)

    def make(i, status, start):
        return EngineInstance(
            id="", status=status, start_time=start, end_time=start,
            engine_id="e", engine_version="0", engine_variant="default",
            engine_factory="f", batch=f"b{i}",
        )

    id1 = repo.insert(make(1, "COMPLETED", t))
    id2 = repo.insert(make(2, "COMPLETED", t + _dt.timedelta(minutes=5)))
    repo.insert(make(3, "FAILED", t + _dt.timedelta(minutes=9)))
    latest = repo.get_latest_completed("e", "0", "default")
    assert latest.id == id2
    assert latest.start_time == t + _dt.timedelta(minutes=5)  # tz survives
    assert [i.id for i in repo.get_completed("e", "0", "default")] == [id1, id2][::-1]

    inst = repo.get(id1)
    inst.status = "FAILED"
    repo.update(inst)
    assert repo.get(id1).status == "FAILED"


def test_model_blobs_over_rest(rest_storage):
    _, client = rest_storage
    blob = bytes(range(256)) * 41  # binary, non-UTF8
    client.models().insert(Model(id="inst-1", models=blob))
    assert client.models().get("inst-1").models == blob
    assert client.models().get("missing") is None
    client.models().delete("inst-1")
    assert client.models().get("inst-1") is None


def test_auth_key_required(memory_storage):
    server = StorageServer(
        storage=memory_storage, host="127.0.0.1", port=0, auth_key="sekret"
    ).start()
    try:
        unauthed = _client_storage(server.port)
        with pytest.raises(StorageError):
            unauthed.apps().get_all()
        assert unauthed.client_for("METADATA").health_check() is False
        authed = _client_storage(server.port, auth_key="sekret")
        assert authed.apps().get_all() == []
        assert authed.client_for("METADATA").health_check() is True
    finally:
        server.stop()


def test_status_verifies_rest_repos(rest_storage):
    _, client = rest_storage
    assert client.verify_all_data_objects() == {
        "METADATA": True, "EVENTDATA": True, "MODELDATA": True,
    }
    dead = _client_storage(1)  # nothing listens on port 1
    assert not any(dead.verify_all_data_objects().values())


# ---------------------------------------------------------------------------
# Cross-host: train on host A, deploy on host B (VERDICT r1 item 3)
# ---------------------------------------------------------------------------

_TRAIN_A = """
from predictionio_tpu.core import Engine, EngineParams
from predictionio_tpu.data.storage import get_storage
from predictionio_tpu.workflow.train import run_train
from tests.sample_engine import Algo0, DataSource0, IdParams, Preparator0, Serving0

engine = Engine(
    data_source_classes={"ds": DataSource0},
    preparator_classes={"prep": Preparator0},
    algorithm_classes={"algo": Algo0},
    serving_classes={"serve": Serving0},
)
ep = EngineParams(
    data_source_params=("ds", IdParams(id=1)),
    preparator_params=("prep", IdParams(id=2)),
    algorithm_params_list=[("algo", IdParams(id=7))],
    serving_params=("serve", IdParams(id=9)),
)
instance = run_train(engine, ep, engine_id="xhost", storage=get_storage())
print("TRAINED", instance.id)
"""

_DEPLOY_B = """
from predictionio_tpu.core import Engine
from predictionio_tpu.data.storage import get_storage
from predictionio_tpu.workflow.deploy import prepare_deploy
from tests.sample_engine import Algo0, DataSource0, Preparator0, Query, Serving0

storage = get_storage()
status = storage.verify_all_data_objects()
assert all(status.values()), status
engine = Engine(
    data_source_classes={"ds": DataSource0},
    preparator_classes={"prep": Preparator0},
    algorithm_classes={"algo": Algo0},
    serving_classes={"serve": Serving0},
)
instance = storage.engine_instances().get_latest_completed("xhost", "0", "default")
assert instance is not None, "instance trained on host A not visible on host B"
deployment = prepare_deploy(engine, instance, storage=storage)
prediction = deployment.query(Query(q=21))
print("SERVED", prediction.q, prediction.algo_id)
"""


def _host_env(tmp_path, name: str, port: int) -> dict:
    """Host env: private localfs root; METADATA+MODELDATA shared via rest."""
    root = tmp_path / name
    root.mkdir()
    env = dict(os.environ)
    env.pop("PYTEST_CURRENT_TEST", None)
    env.update(
        {
            "PYTHONPATH": REPO_ROOT,
            "JAX_PLATFORMS": "cpu",
            "PIO_STORAGE_SOURCES_LOCAL_TYPE": "localfs",
            "PIO_STORAGE_SOURCES_LOCAL_PATH": str(root),
            "PIO_STORAGE_SOURCES_CENTRAL_TYPE": "rest",
            "PIO_STORAGE_SOURCES_CENTRAL_HOSTS": "127.0.0.1",
            "PIO_STORAGE_SOURCES_CENTRAL_PORTS": str(port),
            "PIO_STORAGE_SOURCES_CENTRAL_AUTH_KEY": "xhost-secret",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "events",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "LOCAL",
            "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "meta",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "CENTRAL",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "models",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "CENTRAL",
        }
    )
    return env


def test_train_on_host_a_deploy_on_host_b(tmp_path):
    """Two processes, two private localfs roots, one shared REST tier:
    the workflow the reference runs over ES metadata + HDFS models
    (hdfs/HDFSModels.scala:28)."""
    shared = tmp_path / "shared"
    shared.mkdir()
    central = Storage.from_env(
        {
            "PIO_STORAGE_SOURCES_FS_TYPE": "localfs",
            "PIO_STORAGE_SOURCES_FS_PATH": str(shared),
            "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "meta",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "FS",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "events",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "FS",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "models",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "FS",
        }
    )
    server = StorageServer(
        storage=central, host="127.0.0.1", port=0, auth_key="xhost-secret"
    ).start()
    try:
        a = subprocess.run(
            [sys.executable, "-c", _TRAIN_A], cwd=REPO_ROOT, text=True,
            env=_host_env(tmp_path, "hostA", server.port),
            capture_output=True, timeout=120,
        )
        assert a.returncode == 0, a.stdout + a.stderr
        assert "TRAINED" in a.stdout

        b = subprocess.run(
            [sys.executable, "-c", _DEPLOY_B], cwd=REPO_ROOT, text=True,
            env=_host_env(tmp_path, "hostB", server.port),
            capture_output=True, timeout=120,
        )
        assert b.returncode == 0, b.stdout + b.stderr
        assert "SERVED 21 7" in b.stdout

        # the model blob physically lives in the shared tier, not A or B
        models_dir = shared / "models"
        assert any(models_dir.iterdir())
    finally:
        server.stop()


def test_two_writers_share_one_logical_eventdata(rest_storage):
    """Two rest clients (distinct client objects, same server) see one
    consistent event store — the multi-host EVENTDATA story (VERDICT r1
    item 5 option a; ref: HBEventsUtil.scala:47 shared HBase tables)."""
    _, client_a = rest_storage
    server_port = client_a.client_for("EVENTDATA").config["PORTS"]
    client_b = _client_storage(int(server_port))

    client_a.events().init(7)
    t0 = _dt.datetime(2026, 2, 1, tzinfo=UTC)
    for h, (client, uid) in enumerate([(client_a, "a"), (client_b, "b")] * 3):
        client.events().insert(
            _event("view", f"u-{uid}", f"i{h}", t0 + _dt.timedelta(hours=h)), 7
        )
    seen_a = client_a.events().find(7)
    seen_b = client_b.events().find(7)
    assert len(seen_a) == 6
    assert [e.event_id for e in seen_a] == [e.event_id for e in seen_b]
    # a delete through one host is immediately visible to the other
    assert client_b.events().delete(seen_a[0].event_id, 7)
    assert len(client_a.events().find(7)) == 5


def test_columnar_bulk_roundtrip_over_rest(rest_storage):
    """Bulk training reads/ingest travel as binary npz — 20M-row scale
    without per-event JSON (the region-scan role of HBPEvents.scala:48,
    over the wire)."""
    import numpy as np

    from predictionio_tpu.data.storage import EventColumns

    _, client = rest_storage
    client.events().init(3)
    cols = EventColumns(
        entity_codes=np.array([0, 1, 0], np.int32),
        target_codes=np.array([0, 1, -1], np.int32),
        name_codes=np.array([0, 0, 1], np.int32),
        values=np.array([4.5, np.nan, np.nan], np.float64),
        times_us=np.array([1_000_000, 2_000_000, 3_000_000], np.int64),
        entity_vocab=["anna", "bo"],
        target_vocab=["x1", "x2"],
        names=["rate", "$set"],
    )
    n = client.events().insert_columnar(
        cols, 3, entity_type="user", target_entity_type="item",
        value_property="rating",
    )
    assert n == 3

    back = client.events().find_columnar(
        3, value_property="rating", time_ordered=False
    )
    assert len(back) == 3
    resolved = {
        (back.entity_vocab[back.entity_codes[i]],
         back.target_vocab[back.target_codes[i]] if back.target_codes[i] >= 0 else None,
         back.names[back.name_codes[i]])
        for i in range(3)
    }
    assert resolved == {("anna", "x1", "rate"), ("bo", "x2", "rate"),
                        ("anna", None, "$set")}
    vals = sorted(back.values[~np.isnan(back.values)])
    assert vals == [4.5]
    # filters apply server-side on the bulk route too
    only_rate = client.events().find_columnar(3, event_names=["rate"])
    assert len(only_rate) == 2
    # and the row-level API sees the bulk-ingested events
    events = client.events().find(3)
    assert {e.entity_id for e in events} == {"anna", "bo"}


def test_columnar_rest_edge_cases(rest_storage):
    """Unicode entity types (query-string params), NUL bytes inside ids
    (exact-offset vocab wire format), and loud typo'd filters."""
    import numpy as np

    from predictionio_tpu.data.storage import EventColumns

    _, client = rest_storage
    client.events().init(9)
    cols = EventColumns(
        entity_codes=np.array([0, 1], np.int32),
        target_codes=np.array([0, 0], np.int32),
        name_codes=np.array([0, 0], np.int32),
        values=np.array([1.0, 2.0], np.float64),
        times_us=np.array([1, 2], np.int64),
        entity_vocab=["አበበ", "a\0b"],     # unicode + embedded NUL
        target_vocab=["商品-1"],
        names=["rate"],
    )
    n = client.events().insert_columnar(
        cols, 9, entity_type="ユーザー", target_entity_type="商品",
        value_property="rating",
    )
    assert n == 2
    back = client.events().find_columnar(9, value_property="rating",
                                         time_ordered=False)
    assert sorted(back.entity_vocab[c] for c in back.entity_codes) == \
        sorted(["አበበ", "a\0b"])
    assert back.target_vocab[back.target_codes[0]] == "商品-1"
    rows = client.events().find(9)
    assert {e.entity_type for e in rows} == {"ユーザー"}

    with pytest.raises(TypeError, match="unexpected filters"):
        client.events().find_columnar(9, event_name=["rate"])  # typo
    with pytest.raises(TypeError):   # find()'s fixed signature rejects
        client.events().find(9, entity_types="user")


def test_keepalive_survives_short_circuit_responses(memory_storage):
    """HTTP/1.1 keep-alive: responses sent before the handler reads the
    request body (auth denial, unknown route) must still drain it, or
    the next request on the same connection is parsed from leftover
    body bytes."""
    import http.client

    server = StorageServer(
        storage=memory_storage, host="127.0.0.1", port=0, auth_key="sekret"
    ).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", server.port)
        body = json.dumps({"app_id": 1, "junk": "x" * 4096})
        # 1) denied POST with a body (no auth header)
        conn.request("POST", "/storage/events/init", body=body,
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 401
        resp.read()
        # 2) unknown route with a body, authed
        conn.request("POST", "/storage/events/nope", body=body,
                     headers={"X-PIO-Storage-Key": "sekret"})
        resp = conn.getresponse()
        assert resp.status == 404
        resp.read()
        # 3) a real request on the SAME connection still parses cleanly
        conn.request("POST", "/storage/events/init", body=json.dumps({"app_id": 1}),
                     headers={"X-PIO-Storage-Key": "sekret"})
        resp = conn.getresponse()
        assert resp.status == 200
        assert json.loads(resp.read()) == {"ok": True}
        conn.close()
    finally:
        server.stop()


def test_compact_over_rest(tmp_path):
    """`pio app compact` against a rest-configured client must run the
    compaction ON the storage server's backend and return real stats
    (HBase major-compaction role reached through the network tier)."""
    from tests.test_storage import make_storage

    server_storage = make_storage("eventlog", tmp_path)
    server = StorageServer(storage=server_storage, host="127.0.0.1", port=0).start()
    try:
        client = _client_storage(server.port)
        app = client.apps().insert("rc")
        client.events().init(app.id)
        ids = client.events().insert_batch(
            [_event(eid=f"u{i}") for i in range(40)], app.id)
        for eid in ids[:30]:
            client.events().delete(eid, app.id)
        stats = client.events().compact(app.id)
        assert stats["dropped"] == 30
        assert stats["after_bytes"] < stats["before_bytes"]
        assert len(client.events().find(app.id)) == 10
    finally:
        server.stop()
        server_storage.events().close()


def test_scan_fetch_resumes_after_connection_drop(rest_storage, monkeypatch):
    """A connection that dies mid-transfer of a bulk scan must resume
    from the last received byte (offset fetch), not restart or fail —
    VERDICT r2 item 5 (HBase client retry role)."""
    import urllib.request as _ur

    _, client = rest_storage
    client.events().init(5)
    client.events().insert_batch(
        [_event(eid=f"u{i}", tid=f"i{i % 7}", props={"rating": float(i)})
         for i in range(500)], 5)

    offsets_seen = []
    real_urlopen = _ur.urlopen

    class _DroppingResp:
        """Proxy that yields a first chunk then drops the connection."""

        def __init__(self, resp):
            self._resp = resp
            self._served = False

        def read(self, n=-1):
            if self._served:
                self._resp.close()
                raise ConnectionResetError("injected drop")
            self._served = True
            return self._resp.read(100)  # partial: 100 bytes then die

        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

    state = {"first": True}

    def flaky_urlopen(req, timeout=None):
        url = req.full_url if hasattr(req, "full_url") else req
        if "/storage/events/scan/" in url and "offset=" in url:
            offsets_seen.append(int(url.rsplit("offset=", 1)[1]))
            if state["first"]:
                state["first"] = False
                return _DroppingResp(real_urlopen(req, timeout=timeout))
        return real_urlopen(req, timeout=timeout)

    monkeypatch.setattr(_ur, "urlopen", flaky_urlopen)
    cols = client.events().find_columnar(5, value_property="rating",
                                         time_ordered=True)
    assert len(cols.entity_codes) == 500
    assert [cols.entity_vocab[c] for c in cols.entity_codes[:3]] == \
        ["u0", "u1", "u2"]
    # first fetch started at 0, the resume continued at the 100 received
    # bytes — never from scratch
    assert offsets_seen[0] == 0 and offsets_seen[1] == 100


def test_scan_survives_server_restart_mid_scan(tmp_path):
    """Kill the storage server after the scan was prepared but before
    the fetch, restart it (fresh scan registry), and the client must
    complete correctly by re-preparing — VERDICT r2 item 5 'kill the
    server mid-scan, restarts it, client completes correctly'."""
    from predictionio_tpu.data.backends.rest import RestEventStore

    server_storage = make_memory_storage()
    server1 = StorageServer(storage=server_storage, host="127.0.0.1", port=0).start()
    port = server1.port
    client = _client_storage(port)
    client.events().init(3)
    client.events().insert_batch(
        [_event(eid=f"u{i}", props={"rating": 1.0}) for i in range(50)], 3)

    holder = {"server": server1, "restarted": False}
    orig_fetch = RestEventStore._fetch_scan

    def fetch_with_restart(self, scan_id, total, spool):
        if not holder["restarted"]:
            holder["restarted"] = True
            holder["server"].stop()
            holder["server"] = StorageServer(
                storage=server_storage, host="127.0.0.1", port=port).start()
        return orig_fetch(self, scan_id, total, spool)

    try:
        RestEventStore._fetch_scan = fetch_with_restart
        cols = client.events().find_columnar(3, value_property="rating")
        assert len(cols.entity_codes) == 50
        assert holder["restarted"]
    finally:
        RestEventStore._fetch_scan = orig_fetch
        holder["server"].stop()


def make_memory_storage():
    from predictionio_tpu.data.storage import Storage

    return Storage.from_env({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "meta",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "events",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "models",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })


def test_idempotent_reads_retry_through_transient_outage(tmp_path):
    """An unreachable server raises StorageUnavailableError after
    bounded retries; a server that comes back inside the retry budget
    is transparent to idempotent reads."""
    import threading

    from predictionio_tpu.data.storage import StorageUnavailableError

    server_storage = make_memory_storage()
    probe = StorageServer(storage=server_storage, host="127.0.0.1", port=0).start()
    port = probe.port
    probe.stop()  # port now free; the client will find it dead

    client = _client_storage(port)
    with pytest.raises(StorageUnavailableError):
        client.apps().get_all()

    # bring the server up concurrently with the retried call. Backoff
    # is FULL-jitter now (resilience Policy): individual delays can be
    # ~0, so a generous retry budget — not delay arithmetic — is what
    # makes "comes back inside the budget" deterministic here.
    retry_env = dict(_client_env(port))
    retry_env["PIO_STORAGE_SOURCES_CENTRAL_RETRIES"] = "6"
    client = Storage.from_env(retry_env)
    started = {}

    def bring_up():
        time.sleep(0.05)
        started["server"] = StorageServer(
            storage=server_storage, host="127.0.0.1", port=port).start()

    t = threading.Thread(target=bring_up)
    t.start()
    try:
        assert client.apps().get_all() == []
    finally:
        t.join()
        started["server"].stop()


def test_insert_never_auto_retries(tmp_path):
    """Non-idempotent writes must fail fast on connection errors (a
    blind replay could double-write)."""
    from predictionio_tpu.data.storage import StorageUnavailableError

    probe = StorageServer(storage=make_memory_storage(),
                          host="127.0.0.1", port=0).start()
    port = probe.port
    probe.stop()
    client = _client_storage(port)
    t0 = time.time()
    with pytest.raises(StorageUnavailableError):
        client.events().insert(_event(), 1)
    # no backoff sleeps -> fails in well under the first retry delay
    assert time.time() - t0 < 0.2


def test_strict_json_row_error_maps_to_clean_storage_error(tmp_path):
    """ADVICE r4 (low): a strict=True row-validation failure on the
    server is a PERMANENT client-data error; the rest client must
    surface it as the same clean StorageError the local DAO raises
    synchronously — not a transport-wrapped, retryable-looking server
    fault — and malformed JSON must stay a ValueError (400 route)."""
    import json

    from predictionio_tpu.data.storage import StorageError
    from tests.test_storage import make_storage

    server_storage = make_storage("eventlog", tmp_path)
    server = StorageServer(storage=server_storage, host="127.0.0.1",
                           port=0).start()
    try:
        client = _client_storage(server.port)
        app = client.apps().insert("strictjson")
        client.events().init(app.id)
        bad = json.dumps([
            {"event": "ok", "entityType": "u", "entityId": "u1"},
            {"event": "$badspecial", "entityType": "u", "entityId": "u2"},
        ]).encode()
        with pytest.raises(StorageError) as ei:
            client.events().insert_json_batch(bad, app.id, strict=True)
        # the clean server-side message, not the HTTP-wrapped transport
        # string (local-path parity)
        assert "HTTP 400" not in str(ei.value)
        assert "event 1" in str(ei.value)
        # strict: nothing appended
        assert client.events().find(app.id) == []
        # a body malformed at the array level stays ValueError (the 400
        # ValueError-discriminator path); object-level grammar the
        # native lane declines (e.g. missing member comma) raises
        # JsonRowsUnsupported instead, routing to the Python lane
        with pytest.raises(ValueError):
            client.events().insert_json_batch(
                b'[{"event":"e","entityType":"u","entityId":"x"} '
                b'{"event":"f","entityType":"u","entityId":"y"}]',
                app.id, strict=True)
        # the server survived both client errors
        ids, codes, _, _ = client.events().insert_json_batch(
            json.dumps([{"event": "ok", "entityType": "u",
                         "entityId": "u1"}]).encode(), app.id)
        assert codes == [0]
    finally:
        server.stop()
        server_storage.events().close()
