"""Entity-hash sharded columnar reads (VERDICT r2 item 1/4 substrate).

The reference's bulk read path is region-parallel: each Spark executor
scans only its HBase region slice (hbase/HBPEvents.scala:48), with
regions split by the MD5 rowkey prefix (HBEventsUtil.scala:96-108).
This file covers the TPU build's equivalent: ``stable_hash`` read
shards through ``find_columnar(shard_index=, shard_count=)`` on local
backends and over the REST wire (server-side filtering + scan
counters), plus the shard/merge column algebra they share.
"""

import datetime as _dt
import json
import math
import urllib.request

import numpy as np
import pytest

from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import (
    EventColumns,
    Storage,
    merge_columns,
    shard_columns,
    stable_hash,
)
from predictionio_tpu.serving.storage_server import StorageServer

UTC = _dt.timezone.utc


def _decode(cols: EventColumns):
    """Rows as comparable tuples, independent of code assignment."""
    out = []
    for i in range(len(cols)):
        tc = int(cols.target_codes[i])
        v = float(cols.values[i])
        out.append((
            cols.entity_vocab[cols.entity_codes[i]],
            cols.target_vocab[tc] if tc >= 0 else "",
            cols.names[cols.name_codes[i]],
            -1.0 if math.isnan(v) else v,
            int(cols.times_us[i]),
        ))
    return out


def _synthetic_columns(n=200, n_entities=37, seed=0) -> EventColumns:
    rng = np.random.default_rng(seed)
    ent = rng.integers(0, n_entities, n).astype(np.int32)
    tgt = rng.integers(-1, 11, n).astype(np.int32)
    return EventColumns(
        entity_codes=ent,
        target_codes=tgt,
        name_codes=rng.integers(0, 3, n).astype(np.int32),
        values=rng.random(n),
        times_us=rng.integers(0, 10**9, n).astype(np.int64),
        entity_vocab=[f"u{i}" for i in range(n_entities)],
        target_vocab=[f"i{i}" for i in range(11)],
        names=["rate", "buy", "view"],
    )


def test_shard_columns_partitions_completely():
    cols = _synthetic_columns()
    full = _decode(cols)
    pieces = []
    for k in (4, 3):  # two shardings of the same data
        shards = [shard_columns(cols, i, k) for i in range(k)]
        rows = [r for s in shards for r in _decode(s)]
        assert sorted(rows) == sorted(full)
        for i, s in enumerate(shards):
            # every row routed by its entity's stable hash
            for ent in s.entity_vocab:
                assert stable_hash(ent) % k == i
            # vocabs compacted: every entry referenced by some row
            assert set(s.entity_vocab) == {r[0] for r in _decode(s)}
            used_targets = {r[1] for r in _decode(s)} - {""}
            assert set(s.target_vocab) == used_targets
        pieces.append(shards)
    # shard_count=1 is the identity
    assert shard_columns(cols, 0, 1) is cols


def test_shard_columns_no_targets():
    """Events without target entities ($set/view-style): target_vocab is
    empty and every target_code is -1 — sharding must not crash on the
    size-0 remap table (code-review regression)."""
    cols = _synthetic_columns(n=50)
    cols = EventColumns(
        entity_codes=cols.entity_codes,
        target_codes=np.full(len(cols), -1, np.int32),
        name_codes=cols.name_codes,
        values=cols.values,
        times_us=cols.times_us,
        entity_vocab=cols.entity_vocab,
        target_vocab=[],
        names=cols.names,
    )
    shards = [shard_columns(cols, i, 2) for i in range(2)]
    assert sum(len(s) for s in shards) == len(cols)
    for s in shards:
        assert s.target_vocab == []
        assert np.all(s.target_codes == -1)
    merged = merge_columns(shards)
    assert sorted(_decode(merged)) == sorted(_decode(cols))


def test_merge_columns_reassembles_shards():
    cols = _synthetic_columns()
    shards = [shard_columns(cols, i, 3) for i in range(3)]
    merged = merge_columns(shards)
    assert sorted(_decode(merged)) == sorted(_decode(cols))
    ordered = merge_columns(shards, time_ordered=True)
    times = ordered.times_us
    assert np.all(times[:-1] <= times[1:])
    assert sorted(_decode(ordered)) == sorted(_decode(cols))
    # empty merge
    empty = merge_columns([])
    assert len(empty) == 0 and empty.entity_vocab == []


def _seed_events(store, app_id=1, n=60):
    store.init(app_id)
    events = []
    for i in range(n):
        events.append(Event(
            event="rate",
            entity_type="user",
            entity_id=f"user_{i % 17}",
            target_entity_type="item",
            target_entity_id=f"item_{i % 7}",
            properties={"rating": float(1 + i % 5)},
            event_time=_dt.datetime(2026, 1, 1, tzinfo=UTC)
            + _dt.timedelta(minutes=i),
        ))
    store.insert_batch(events, app_id)
    return events


@pytest.fixture(params=["memory", "eventlog"])
def sharded_store(request, tmp_path):
    from tests.test_storage import make_storage

    storage = make_storage(request.param, tmp_path)
    yield storage.events()


def test_find_columnar_shards_union_to_full_scan(sharded_store):
    store = sharded_store
    _seed_events(store)
    full = store.find_columnar(1, value_property="rating",
                               time_ordered=False)
    shards = [
        store.find_columnar(1, value_property="rating", time_ordered=False,
                            shard_index=i, shard_count=2)
        for i in range(2)
    ]
    assert sum(len(s) for s in shards) == len(full)
    assert 0 < len(shards[0]) < len(full)  # both shards non-trivial
    assert sorted(_decode(merge_columns(shards))) == sorted(_decode(full))
    for i, s in enumerate(shards):
        for ent in s.entity_vocab:
            assert stable_hash(ent) % 2 == i


def test_find_columnar_shard_filter_precedes_limit(sharded_store):
    """A row limit applies AFTER the entity-hash shard filter — the
    shard's first `limit` rows, not the shard subset of the first
    `limit` rows overall (code-review regression)."""
    store = sharded_store
    _seed_events(store)
    full = store.find_columnar(1, time_ordered=True,
                               shard_index=0, shard_count=2)
    limited = store.find_columnar(1, time_ordered=True, limit=5,
                                  shard_index=0, shard_count=2)
    assert len(limited) == 5
    assert list(limited.times_us) == list(full.times_us[:5])
    for ent in limited.entity_vocab:
        assert stable_hash(ent) % 2 == 0

    newest = store.find_columnar(1, time_ordered=True, limit=5,
                                 reversed=True,
                                 shard_index=0, shard_count=2)
    assert list(newest.times_us) == list(full.times_us[-5:][::-1])


def test_find_columnar_shard_param_validation(sharded_store):
    store = sharded_store
    store.init(1)
    with pytest.raises(ValueError):
        store.find_columnar(1, shard_index=0)
    with pytest.raises(ValueError):
        store.find_columnar(1, shard_index=2, shard_count=2)


def test_rest_sharded_scan_and_server_counters(memory_storage):
    """Over the wire: the SERVER applies the shard filter (each host
    fetches ~1/N of the rows) and its /storage/stats log proves it."""
    from tests.test_rest_storage import _client_storage

    _seed_events(memory_storage.events())
    server = StorageServer(storage=memory_storage, host="127.0.0.1",
                           port=0).start()
    try:
        client = _client_storage(server.port).events()
        full = client.find_columnar(1, value_property="rating",
                                    time_ordered=False)
        shards = [
            client.find_columnar(1, value_property="rating",
                                 time_ordered=False,
                                 shard_index=i, shard_count=2)
            for i in range(2)
        ]
        assert sorted(_decode(merge_columns(shards))) == sorted(_decode(full))

        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/storage/stats"
        ) as resp:
            stats = json.loads(resp.read())
        scans = stats["columnar_scans"]
        assert len(scans) == 3
        assert scans[0]["shard_count"] is None
        assert scans[0]["rows"] == len(full)
        sharded = {s["shard_index"]: s["rows"] for s in scans[1:]}
        assert sharded.keys() == {0, 1}
        assert sum(sharded.values()) == len(full)
        # both shards carry a real fraction of the data (17 users split
        # by hash; neither side can be empty or everything)
        assert all(0 < r < len(full) for r in sharded.values())
    finally:
        server.stop()
