"""Long-context attention ops: blockwise and ring vs the materialized
oracle. Ring runs on the 8-virtual-device CPU mesh (conftest), the
session-scale stand-in for a TPU slice's ``seq`` axis."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from predictionio_tpu.ops.attention import (
    blockwise_attention,
    mha_reference,
    ring_attention_sharded,
)
from predictionio_tpu.parallel.mesh import create_mesh


def _qkv(B=2, L=64, H=2, D=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(B, L, H, D)).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
def test_blockwise_matches_reference(causal):
    q, k, v = _qkv()
    ref = mha_reference(q, k, v, causal=causal)
    out = blockwise_attention(q, k, v, block_size=16, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_blockwise_rejects_ragged_blocks():
    q, k, v = _qkv(L=60)
    with pytest.raises(ValueError):
        blockwise_attention(q, k, v, block_size=16)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_reference(causal):
    q, k, v = _qkv(L=64)
    mesh = create_mesh({"seq": 8})
    ref = mha_reference(q, k, v, causal=causal)
    out = ring_attention_sharded(q, k, v, mesh, axis="seq", causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_with_batch_axis():
    q, k, v = _qkv(B=4, L=32)
    mesh = create_mesh({"data": 2, "seq": 4})
    ref = mha_reference(q, k, v, causal=True)
    out = ring_attention_sharded(
        q, k, v, mesh, axis="seq", causal=True, batch_axis="data"
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_jits_and_reuses():
    q, k, v = _qkv(L=32)
    mesh = create_mesh({"seq": 8})
    fn = jax.jit(
        lambda q, k, v: ring_attention_sharded(q, k, v, mesh, axis="seq")
    )
    out1 = fn(q, k, v)
    out2 = fn(q * 0.5, k, v)
    assert out1.shape == q.shape
    assert not np.allclose(np.asarray(out1), np.asarray(out2))


def test_decode_suffix_query():
    """mha_reference supports Lq < Lk (decode): the query block sits at
    the END of the key sequence — the serve-time incremental path."""
    q, k, v = _qkv(L=32)
    ref = mha_reference(q, k, v, causal=True)
    tail = mha_reference(q[:, -4:], k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(tail), np.asarray(ref[:, -4:]), atol=1e-5
    )
