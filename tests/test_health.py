"""Health & SLO subsystem: probes, /healthz + /readyz on every server,
watchdogs, burn-rate math, OpenMetrics exemplars, the push path, and
the admin-auth matrix (obs/health.py, obs/slo.py, obs/push.py,
serving/http.py wiring)."""

import json
import logging
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from predictionio_tpu.core import (
    Algorithm,
    DataSource,
    Engine,
    FirstServing,
    IdentityPreparator,
)
from predictionio_tpu.core.params import EngineParams, Params
from predictionio_tpu.data.storage import Storage
from predictionio_tpu.obs import flight, health, metrics, push, slo, trace
from predictionio_tpu.serving import engine_server as engine_server_mod
from predictionio_tpu.serving.engine_server import EngineServer, MicroBatcher
from predictionio_tpu.serving.event_server import EventServer
from predictionio_tpu.serving.http import HTTPServerBase, JSONRequestHandler
from predictionio_tpu.serving.storage_server import StorageServer
from predictionio_tpu.tools.admin import AdminServer
from predictionio_tpu.tools.dashboard import DashboardServer
from predictionio_tpu.workflow.train import run_train


def get(url, headers=None, method="GET", body=None):
    req = urllib.request.Request(url, headers=headers or {}, method=method,
                                 data=body)
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, resp.read().decode(), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode(), dict(e.headers)


def get_json(url, headers=None, method="GET", body=None):
    status, text, _ = get(url, headers, method, body)
    return status, json.loads(text or "null")


# -- probe registry ------------------------------------------------------------

def test_probe_status_transitions_and_aggregation():
    reg = health.HealthRegistry()
    state = {"status": health.OK}
    reg.register("flappy", lambda: health.ProbeResult(state["status"], "x"))
    reg.register("steady", lambda: health.ok("fine"))

    overall, detail = reg.run()
    assert overall == health.OK
    assert detail["flappy"]["status"] == "ok"
    assert detail["steady"]["latency_ms"] >= 0

    state["status"] = health.DEGRADED
    overall, detail = reg.run()
    assert overall == health.DEGRADED

    state["status"] = health.FAILED
    overall, detail = reg.run()
    assert overall == health.FAILED
    assert detail["flappy"]["reason"] == "x"


def test_raising_probe_is_failed_not_a_crash():
    reg = health.HealthRegistry()

    def boom():
        raise RuntimeError("backend exploded")

    reg.register("boom", boom)
    overall, detail = reg.run()
    assert overall == health.FAILED
    assert "backend exploded" in detail["boom"]["reason"]


def test_probe_registration_is_last_wins():
    reg = health.HealthRegistry()
    reg.register("p", lambda: health.failed("old"))
    reg.register("p", lambda: health.ok("new"))
    overall, detail = reg.run()
    assert overall == health.OK and detail["p"]["reason"] == "new"
    reg.unregister("p")
    assert reg.names() == []


def test_queue_depth_probe():
    assert health.queue_depth_probe(lambda: 2, 10)().status == health.OK
    deep = health.queue_depth_probe(lambda: 10, 10)()
    assert deep.status == health.DEGRADED and "10" in deep.reason
    assert health.queue_depth_probe(lambda: None, 10)().status == health.OK


def test_probe_results_land_in_metrics():
    reg = health.HealthRegistry()
    reg.register("metricated", lambda: health.degraded("meh"))
    reg.run()
    gauge = metrics.REGISTRY.get("pio_health_probe_status")
    assert gauge.labels("metricated").value == 1.0  # degraded rank


# -- /healthz + /readyz on every server ---------------------------------------

from dataclasses import dataclass


@dataclass
class ConstParams(Params):
    value: float = 1.0


class ConstDataSource(DataSource):
    def __init__(self, params: ConstParams):
        super().__init__(params)

    def read_training(self, ctx):
        return self.params.value


class ConstAlgo(Algorithm):
    def __init__(self, params: ConstParams):
        super().__init__(params)

    def train(self, ctx, pd):
        return pd + self.params.value

    def predict(self, model, query):
        return {"result": model * query["mult"]}


def train_const(storage):
    engine = Engine(ConstDataSource, IdentityPreparator,
                    {"const": ConstAlgo}, FirstServing)
    ep = EngineParams(
        data_source_params=("", ConstParams(value=1.0)),
        preparator_params=("", None),
        algorithm_params_list=[("const", ConstParams(value=2.0))],
        serving_params=("", None),
    )
    return engine, run_train(engine, ep, engine_id="const", storage=storage)


def test_every_server_answers_healthz_and_readyz(memory_storage):
    engine, _ = train_const(memory_storage)
    servers = [
        EventServer(storage=memory_storage, host="127.0.0.1", port=0),
        EngineServer(engine, "const", host="127.0.0.1", port=0,
                     storage=memory_storage),
        StorageServer(storage=memory_storage, host="127.0.0.1", port=0),
        DashboardServer(storage=memory_storage, host="127.0.0.1", port=0),
        AdminServer(storage=memory_storage, host="127.0.0.1", port=0),
    ]
    try:
        for server in servers:
            server.start()
            base = f"http://127.0.0.1:{server.port}"
            status, body = get_json(f"{base}/healthz")
            assert status == 200 and body == {"status": "alive"}, type(server)
            status, body = get_json(f"{base}/readyz")
            assert status == 200, (type(server), body)
            assert body["status"] in ("ok", "degraded")
            # the per-server storage probe ran against live storage
            assert body["probes"]["storage"]["status"] == "ok"
            assert "devices" in body["probes"]
    finally:
        for server in servers:
            server.stop()


def test_readyz_503_when_storage_backend_is_down(tmp_path):
    storage = Storage.from_env({
        "PIO_STORAGE_SOURCES_SQL_TYPE": "sqlite",
        "PIO_STORAGE_SOURCES_SQL_PATH": str(tmp_path / "pio.db"),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "SQL",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "SQL",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "SQL",
    })
    server = EventServer(storage=storage, host="127.0.0.1", port=0).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        status, body = get_json(f"{base}/readyz")
        assert status == 200 and body["probes"]["storage"]["status"] == "ok"
        # kill the backend: every query on the closed handle now raises
        storage.client_for("METADATA").close()
        status, body = get_json(f"{base}/readyz")
        assert status == 503
        assert body["status"] == "failed"
        assert body["probes"]["storage"]["status"] == "failed"
        assert body["probes"]["storage"]["reason"]  # names the repos
        # liveness is unaffected: the process still answers
        assert get_json(f"{base}/healthz")[0] == 200
    finally:
        server.stop()


def test_sqlite_health_check_round_trips(tmp_path):
    from predictionio_tpu.data.backends.sqlite import SqliteStorageClient

    client = SqliteStorageClient({"PATH": str(tmp_path / "h.db")})
    assert client.health_check() is True
    client.close()
    with pytest.raises(Exception):
        client.health_check()


# -- watchdogs -----------------------------------------------------------------

def _wait_for(predicate, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


def stall_count(name):
    family = metrics.REGISTRY.get("pio_watchdog_stall_total")
    return family.labels(name).value


def test_watchdog_fires_on_stalled_work(caplog):
    wd = health.Watchdog("t-stall", min_seconds=0.01, min_history=1,
                         factor=5.0)
    with wd.watch():
        pass  # ~instant: trailing median ≈ 0 -> deadline = 0.01 * 5
    before = stall_count("t-stall")
    token = trace.activate("feedfacefeedfacefeedfacefeedface")
    try:
        with caplog.at_level(logging.WARNING, logger="pio.stall"):
            with wd.watch():
                assert _wait_for(
                    lambda: stall_count("t-stall") == before + 1)
    finally:
        trace.deactivate(token)
    records = [r for r in caplog.records if r.name == "pio.stall"]
    assert records, "stall log line missing"
    payload = records[-1].pio
    assert payload["watchdog"] == "t-stall"
    assert payload["trace"] == "feedfacefeedfacefeedfacefeedface"


def test_watchdog_fires_once_per_watch_and_records_history():
    wd = health.Watchdog("t-once", min_seconds=0.01, min_history=1,
                         factor=2.0)
    with wd.watch():
        pass
    before = stall_count("t-once")
    with wd.watch():
        _wait_for(lambda: stall_count("t-once") == before + 1)
        time.sleep(0.15)  # well past a second deadline's worth
    assert stall_count("t-once") == before + 1
    assert wd.deadline_seconds() is not None


def test_watchdog_not_armed_without_history():
    wd = health.Watchdog("t-cold", min_seconds=0.01, min_history=8)
    assert wd.deadline_seconds() is None
    before = stall_count("t-cold")
    with wd.watch():
        time.sleep(0.05)
    assert stall_count("t-cold") == before


def test_deadman_stall_dumps_stacks(tmp_path, monkeypatch):
    monkeypatch.setenv("PIO_FLIGHT_DIR", str(tmp_path))
    wd = health.Watchdog("t-train", min_seconds=0.01, min_history=1,
                         factor=2.0, dump_stacks=True)
    before = stall_count("t-train")
    with wd.deadman():
        wd.beat(0.005)  # history lands; deadline becomes ~0.02s
        assert _wait_for(lambda: stall_count("t-train") == before + 1)
    dumps = [f for f in os.listdir(tmp_path)
             if f.startswith("stall-t-train")]
    assert dumps, "stack dump file missing"
    with open(tmp_path / dumps[0]) as f:
        doc = json.load(f)
    assert doc["stall"]["watchdog"] == "t-train"
    assert doc["threads"]  # every thread's stack captured


def test_deadman_beat_resets_deadline():
    wd = health.Watchdog("t-beat", min_seconds=0.05, min_history=1,
                         factor=2.0)
    before = stall_count("t-beat")
    with wd.deadman():
        for _ in range(6):
            wd.beat(0.04)  # deadline 0.1s, beaten every ~0.04s
            time.sleep(0.04)
    assert stall_count("t-beat") == before


def test_start_deadman_concurrent_arms_exactly_once(monkeypatch):
    """Regression (graftlint JT20): two threads racing through
    start_deadman() must converge on ONE armed monitor entry — the old
    check-then-arm split let both arm, leaking a watch that fired
    forever because beats re-armed only the recorded key."""
    wd = health.Watchdog("t-arm-race", min_seconds=0.05, min_history=1,
                         factor=2.0)
    barrier = threading.Barrier(2)
    real_arm = health._MONITOR.arm

    def synced_arm(watch):
        # both threads are past the armed-already check before either
        # arms: the widest possible race window, deterministically
        barrier.wait(timeout=5)
        return real_arm(watch)

    monkeypatch.setattr(health._MONITOR, "arm", synced_arm)
    threads = [threading.Thread(target=wd.start_deadman) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=5)
    try:
        with health._MONITOR._cond:
            mine = [k for k, w in health._MONITOR._watches.items()
                    if w.watchdog is wd]
        assert len(mine) == 1, f"expected one armed watch, got {mine}"
        assert wd._deadman_key == mine[0]
    finally:
        with wd._lock:
            key, wd._deadman_key = wd._deadman_key, None
        if key is not None:
            health._MONITOR.disarm(key)


def test_microbatcher_dispatch_stall_fires_watchdog(monkeypatch):
    tight = health.Watchdog("serving-dispatch-test", min_seconds=0.01,
                            min_history=1, factor=2.0)
    monkeypatch.setattr(engine_server_mod, "_DISPATCH_WATCHDOG", tight)
    delay = {"sec": 0.0}

    def run_one(payload):
        time.sleep(delay["sec"])
        return payload

    batcher = MicroBatcher(lambda ps: [run_one(p) for p in ps], run_one)
    try:
        batcher.submit("warm")  # builds the trailing history
        before = stall_count("serving-dispatch-test")
        delay["sec"] = 0.25
        batcher.submit("slow")
        assert _wait_for(
            lambda: stall_count("serving-dispatch-test") == before + 1)
    finally:
        batcher.stop()


def test_microbatcher_registers_queue_probe(monkeypatch):
    batcher = MicroBatcher(lambda ps: ps, lambda p: p)
    try:
        assert "serving_queue" in health.REGISTRY.names()
        _, detail = health.REGISTRY.run()
        assert detail["serving_queue"]["status"] == "ok"
    finally:
        batcher.stop()
    assert "serving_queue" not in health.REGISTRY.names()


def test_worker_loop_survives_internal_failure():
    """An exception escaping the dispatch path fails THAT batch's
    waiters and is logged — the worker thread stays alive for the
    next submit (the JT09 hazard, fixed)."""
    calls = {"n": 0}

    def run_one(payload):
        calls["n"] += 1
        return payload

    batcher = MicroBatcher(lambda ps: [run_one(p) for p in ps], run_one)
    try:
        # sabotage a non-dispatch internal: _record_splits raising must
        # not kill the worker loop
        original = batcher._record_splits

        def explode(*a, **k):
            batcher._record_splits = original
            raise RuntimeError("bookkeeping bug")

        batcher._record_splits = explode
        with pytest.raises(RuntimeError):
            batcher.submit("a")
        assert batcher.submit("b") == "b"  # worker still alive
    finally:
        batcher.stop()


# -- SLO burn-rate math --------------------------------------------------------

def test_burn_rate_math_on_synthetic_series():
    budget = 0.01  # objective 0.99
    t0 = 1_000_000.0
    steady = [(t0 + i * 60, 1000.0 + 100 * i, 1000.0 + 100 * i)
              for i in range(10)]
    assert slo.burn_rate(steady, t0 + 540, 300.0, budget) == 0.0

    # next 5m after the steady run: 100 requests, all bad -> error rate
    # 1.0 over that window -> burn 100 (baseline = the t0+540 sample)
    regressed = steady + [(t0 + 840, steady[-1][1], steady[-1][2] + 100)]
    burn = slo.burn_rate(regressed, t0 + 840, 300.0, budget)
    assert burn == pytest.approx(100.0)

    # half bad -> burn 50
    half = steady + [(t0 + 840, steady[-1][1] + 50, steady[-1][2] + 100)]
    assert slo.burn_rate(half, t0 + 840, 300.0, budget) == pytest.approx(50.0)

    assert slo.burn_rate([], t0, 300.0, budget) is None
    assert slo.burn_rate(steady[:1], t0, 300.0, budget) is None
    # no traffic in the window -> None, not 0
    flat = [(t0, 10.0, 10.0), (t0 + 300, 10.0, 10.0)]
    assert slo.burn_rate(flat, t0 + 300, 300.0, budget) is None


def test_multiwindow_alert_requires_both_windows():
    mon = slo.SLOMonitor([slo.SLO(name="t-avail", kind="availability",
                                  metric="nonexistent", objective=0.99)])
    t0 = 2_000_000.0
    # long healthy history, then a 450-request 100%-error burst younger
    # than 5m: the 5m window burns hot (450/2850 = 15.8x budget) but 1h
    # dilutes it below threshold (450/35850 = 1.3x) -> the fast page
    # holds until the burst persists into the long window too
    for i in range(61):
        mon.record("t-avail", t0 + i * 60, 36000.0 + 600 * i,
                   36000.0 + 600 * i)
    last_good, last_total = 36000.0 + 600 * 60, 36000.0 + 600 * 60
    mon.record("t-avail", t0 + 61 * 60, last_good, last_total + 450)
    report = mon.evaluate(now=t0 + 61 * 60)
    entry = report["slos"][0]
    assert entry["burn_rates"]["5m"] >= slo.FAST_BURN
    assert entry["burn_rates"]["1h"] < slo.FAST_BURN
    assert entry["state"] == "ok"


def test_latency_regression_fires_fast_burn_alert():
    """Acceptance: a synthetic latency regression on the REAL
    pio_serving_request_seconds histogram drives the fast-window
    burn-rate alert to firing."""
    hist = metrics.REGISTRY.get("pio_serving_request_seconds")
    child = hist.labels("slo-regression-test")
    slo_def = slo.SLO(name="t-latency", kind="latency",
                      metric="pio_serving_request_seconds",
                      objective=0.99, threshold_ms=100.0)
    mon = slo.SLOMonitor([slo_def])
    t0 = 3_000_000.0
    # healthy traffic: all under the 100ms threshold
    for _ in range(200):
        child.observe(0.005)
    good, total = slo_def.measure()
    mon.record("t-latency", t0, good, total)
    # regression: the next wave blows through the threshold
    for _ in range(200):
        child.observe(0.5)
    good, total = slo_def.measure()
    mon.record("t-latency", t0 + 240, good, total)
    report = mon.evaluate(now=t0 + 240)
    entry = report["slos"][0]
    assert entry["burn_rates"]["5m"] >= slo.FAST_BURN
    assert entry["alerts"]["fast"]["firing"] is True
    assert entry["state"] == "firing"
    hist.remove("slo-regression-test")


def test_slo_monitor_rides_flight_snapshot_cadence():
    assert "slo" in {
        name for name, _fn in flight._snapshot_listeners
    }, "SLO sampler not registered on the flight snapshot cadence"


def test_admin_slo_endpoint_and_cli(memory_storage, capsys):
    server = EventServer(storage=memory_storage, host="127.0.0.1",
                         port=0).start()
    try:
        status, body = get_json(
            f"http://127.0.0.1:{server.port}/admin/slo")
        assert status == 200
        names = {e["name"] for e in body["slos"]}
        assert {"serving-latency", "http-availability"} <= names
    finally:
        server.stop()
    from predictionio_tpu.tools.cli import main

    assert main(["slo"]) in (0, 1)
    out = capsys.readouterr().out
    assert "serving-latency" in out and "http-availability" in out


# -- OpenMetrics + exemplars ---------------------------------------------------

def test_openmetrics_document_shape():
    c = metrics.counter("pio_test_om_total", "om test counter", ("k",))
    c.labels("v").inc(3)
    h = metrics.histogram("pio_test_om_seconds", "om test histogram",
                          buckets=(0.1, 1.0))
    h.observe(0.05, exemplar={"trace_id": "abcd1234abcd1234"})
    text = metrics.REGISTRY.render_openmetrics()
    assert text.endswith("# EOF\n")
    # counter family drops _total, the sample keeps it
    assert "# TYPE pio_test_om counter" in text
    assert 'pio_test_om_total{k="v"} 3' in text
    # exemplar rides the bucket the observation landed in
    assert ('pio_test_om_seconds_bucket{le="0.1"} 1 '
            '# {trace_id="abcd1234abcd1234"} 0.05') in text
    # the Prometheus document is unchanged (no exemplars, no EOF)
    prom = metrics.REGISTRY.render()
    assert "# {" not in prom and "# EOF" not in prom
    assert "pio_test_om_total" in prom


def test_exemplar_carries_served_request_trace_id(memory_storage):
    """Acceptance: OpenMetrics exposition carries an exemplar bearing a
    real trace id from a served request."""
    from predictionio_tpu.data.metadata import AccessKey

    app = memory_storage.apps().insert("health-ex-app")
    memory_storage.events().init(app.id)
    key = AccessKey.generate(app.id)
    memory_storage.access_keys().insert(key)
    server = EventServer(storage=memory_storage, host="127.0.0.1",
                         port=0).start()
    trace_id = "cafe0123cafe0123cafe0123cafe0123"
    try:
        base = f"http://127.0.0.1:{server.port}"
        status, _, _ = get(
            f"{base}/events.json?accessKey={key.key}",
            headers={"Content-Type": "application/json",
                     trace.TRACE_HEADER: trace_id},
            method="POST",
            body=json.dumps({"event": "view", "entityType": "user",
                             "entityId": "u1"}).encode(),
        )
        assert status == 201
        status, text, headers = get(
            f"{base}/metrics",
            headers={"Accept": "application/openmetrics-text"})
        assert status == 200
        assert "application/openmetrics-text" in headers["Content-Type"]
        exemplar_lines = [l for l in text.splitlines()
                          if f'trace_id="{trace_id}"' in l]
        assert exemplar_lines, "no exemplar carrying the request trace id"
        assert all(" # {" in l for l in exemplar_lines)
        # content negotiation: default Accept still gets Prometheus text
        _, prom_text, prom_headers = get(f"{base}/metrics")
        assert "version=0.0.4" in prom_headers["Content-Type"]
        assert "# EOF" not in prom_text
    finally:
        server.stop()


# -- push path -----------------------------------------------------------------

class _FlakySink:
    """HTTP sink failing the first N pushes, then accepting."""

    def __init__(self, fail_first=1):
        self.hits = []
        self.fail_first = fail_first
        sink = self

        class Handler(JSONRequestHandler):
            server_version = "FlakySink/0.1"

            def do_POST(self):
                body = self._read_body()
                sink.hits.append(body)
                if len(sink.hits) <= sink.fail_first:
                    self._send(503, {"message": "not yet"})
                else:
                    self._send(200, {"message": "ok"})

        self.server = HTTPServerBase("127.0.0.1", 0, Handler).start()

    @property
    def url(self):
        return f"http://127.0.0.1:{self.server.port}/push"

    def stop(self):
        self.server.stop()


def test_pusher_retries_flaky_sink_with_backoff():
    sink = _FlakySink(fail_first=1)
    pusher = push.MetricsPusher(sink.url, interval=0.05, max_backoff=0.2)
    try:
        pusher.start()
        assert _wait_for(lambda: len(sink.hits) >= 3)
    finally:
        pusher.stop()
        sink.stop()
    # the pushed document is OpenMetrics (exemplar-capable)
    assert sink.hits[-1].rstrip().endswith(b"# EOF")
    family = metrics.REGISTRY.get("pio_push_total")
    assert family.labels("ok").value >= 1
    assert family.labels("error").value >= 1


def test_pusher_push_once_never_raises_on_dead_sink():
    pusher = push.MetricsPusher("http://127.0.0.1:9/push", timeout=0.2)
    assert pusher.push_once() is False


def test_pusher_starts_from_env(monkeypatch):
    sink = _FlakySink(fail_first=0)
    monkeypatch.setenv("PIO_PUSH_URL", sink.url)
    monkeypatch.setenv("PIO_PUSH_INTERVAL_SEC", "0.05")
    try:
        pusher = push.start_from_env()
        assert pusher is not None
        assert push.start_from_env() is pusher  # idempotent
        assert _wait_for(lambda: len(sink.hits) >= 1)
    finally:
        push.stop()
        sink.stop()


# -- admin auth ----------------------------------------------------------------

def test_admin_auth_matrix(memory_storage, monkeypatch):
    server = EventServer(storage=memory_storage, host="127.0.0.1",
                         port=0).start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        # no token configured: everything open (trusted-network default)
        assert get(f"{base}/admin/flight")[0] == 200
        monkeypatch.setenv("PIO_ADMIN_TOKEN", "s3cret")
        # /admin/* routes 401 without / with a wrong bearer
        for path, method in (("/admin/flight", "GET"),
                             ("/admin/slo", "GET"),
                             ("/admin/profile?seconds=1", "POST")):
            body = b"" if method == "POST" else None
            status, text, headers = get(f"{base}{path}", method=method,
                                        body=body)
            assert status == 401, (path, status)
            assert headers.get("WWW-Authenticate") == "Bearer"
            assert get(f"{base}{path}",
                       headers={"Authorization": "Bearer wrong"},
                       method=method, body=body)[0] == 401
        # correct bearer: through (profile may 501 on CPU — not 401)
        auth = {"Authorization": "Bearer s3cret"}
        assert get(f"{base}/admin/flight", headers=auth)[0] == 200
        assert get(f"{base}/admin/slo", headers=auth)[0] == 200
        assert get(f"{base}/admin/profile?seconds=1", headers=auth,
                   method="POST", body=b"")[0] != 401
        # scraping + probing surfaces stay unauthenticated
        assert get(f"{base}/healthz")[0] == 200
        assert get(f"{base}/readyz")[0] == 200
        assert get(f"{base}/metrics")[0] == 200
    finally:
        server.stop()


# -- flight-dir growth cap -----------------------------------------------------

def test_flight_dump_dir_is_capped(tmp_path, monkeypatch):
    monkeypatch.setenv("PIO_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("PIO_FLIGHT_MAX_DUMPS", "3")
    evicted = metrics.REGISTRY.get("pio_flight_dumps_evicted_total")
    before = evicted.value
    paths = []
    for i in range(6):
        path = flight.write_dump_file(f"flight-test{i}", {"i": i})
        assert path is not None
        os.utime(path, (1_700_000_000 + i, 1_700_000_000 + i))
        paths.append(path)
    remaining = sorted(f for f in os.listdir(tmp_path)
                       if f.endswith(".json"))
    assert len(remaining) == 3
    # oldest evicted first: the newest dump always survives
    assert os.path.basename(paths[-1]) in remaining
    assert os.path.basename(paths[0]) not in remaining
    assert evicted.value >= before + 3


def test_flight_dump_byte_cap(tmp_path, monkeypatch):
    monkeypatch.setenv("PIO_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("PIO_FLIGHT_MAX_DUMPS", "100")
    monkeypatch.setenv("PIO_FLIGHT_MAX_DUMP_BYTES", "300")
    for i in range(5):
        path = flight.write_dump_file(f"fat{i}", {"pad": "x" * 100})
        os.utime(path, (1_700_000_000 + i, 1_700_000_000 + i))
    total = sum(
        os.path.getsize(os.path.join(tmp_path, f))
        for f in os.listdir(tmp_path) if f.endswith(".json"))
    assert total <= 300
    assert any(f.startswith("fat4") for f in os.listdir(tmp_path))


def test_error_dump_goes_through_capped_writer(tmp_path, monkeypatch):
    monkeypatch.setenv("PIO_FLIGHT_DIR", str(tmp_path))
    recorder = flight.FlightRecorder(capacity=8)
    key = recorder.begin("a" * 32, "TestSrv", "GET", "/boom")
    recorder.finish(key, 500, "RuntimeError: boom")
    dumps = [f for f in os.listdir(tmp_path) if f.startswith("flight-")]
    assert len(dumps) == 1
