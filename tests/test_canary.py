"""Canary analysis e2e (ROADMAP item D acceptance): a candidate lands
on exactly one replica through the rolling-swap machinery, the router
tags per-lane latency and samples paired answers, and the verdict
(obs/quality.py) auto-promotes a good candidate / auto-rolls-back a
degraded one — with zero non-429 client errors throughout, and every
surface (gauges, GET /admin/quality, pio canary) reading the same
underlying numbers."""

from __future__ import annotations

import contextlib
import json
import threading
import time
import urllib.request

import pytest

from predictionio_tpu.obs import quality
from predictionio_tpu.resilience import chaos
from predictionio_tpu.serving.engine_server import EngineServer
from predictionio_tpu.serving.fleet import (READY, FleetSupervisor,
                                            threaded_fleet)
from predictionio_tpu.serving.router import QueryRouter
from predictionio_tpu.workflow.deploy import latest_completed_instance_id

from tests.test_fleet import post
from tests.test_health import get_json, train_const


@pytest.fixture(autouse=True)
def _clean_quality_state():
    quality.STATE.clear()
    yield
    quality.STATE.clear()


@contextlib.contextmanager
def canary_fleet(storage, engine, n=3, canary_mode=None):
    """N threaded const-engine replicas behind a router, with the
    version source the canary lane needs (running_fleet in test_fleet
    has none)."""
    def factory(name):
        return EngineServer(engine, "const", host="127.0.0.1", port=0,
                            storage=storage, max_batch=8, chaos_tag=name)

    fleet = FleetSupervisor(
        threaded_fleet(n, factory), probe_interval=0.05,
        version_source=lambda: latest_completed_instance_id(
            storage, "const"),
        canary_mode=canary_mode,
    ).start()
    router = None
    try:
        assert fleet.wait_ready(timeout=60), fleet.snapshot()
        router = QueryRouter(fleet, host="127.0.0.1", port=0).start()
        yield fleet, router, f"http://127.0.0.1:{router.port}"
    finally:
        chaos.clear()
        if router is not None:
            router.stop()
        fleet.stop()


def _await(predicate, timeout=60.0, message="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {message}")


@contextlib.contextmanager
def _load(base, failures, results):
    """Continuous client load through the router; every non-(200|429)
    answer and every transport error is a recorded failure."""
    stop_evt = threading.Event()

    def loader():
        while not stop_evt.is_set():
            try:
                status, body, _ = post(base + "/queries.json")
                results.append(status)
                if status not in (200, 429):
                    failures.append((status, body[:200]))
            except Exception as e:  # noqa: BLE001 — a transport error
                # IS the outage the canary machinery must prevent
                failures.append(("transport", repr(e)))

    threads = [threading.Thread(target=loader) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        yield
    finally:
        stop_evt.set()
        for t in threads:
            t.join(timeout=30)


def test_good_candidate_is_auto_promoted(memory_storage, monkeypatch):
    """Acceptance half 1: a healthy candidate (identical answers, clean
    latency) collects paired samples and is auto-promoted through the
    rolling swap — zero non-429 errors end to end."""
    monkeypatch.setenv("PIO_CANARY_MIN_PAIRS", "4")
    monkeypatch.setenv("PIO_CANARY_SAMPLE_EVERY", "1")
    monkeypatch.setenv("PIO_DRAIN_TIMEOUT", "5")
    # CI jitter must not read as a latency regression in this half
    monkeypatch.setenv("PIO_SLO_LATENCY_MS", "2000")
    engine, baseline_instance = train_const(memory_storage)
    with canary_fleet(memory_storage, engine) as (fleet, router, base):
        _, candidate = train_const(memory_storage)
        assert candidate.id != baseline_instance.id
        failures, results = [], []
        with _load(base, failures, results):
            status, body, _ = post(
                base + "/admin/fleet",
                body=json.dumps({"canary": "start"}).encode())
            assert status == 202, body
            _await(lambda: fleet.canary().get("active"),
                   message="canary active")
            info = fleet.canary()
            assert info["baseline_version"] == baseline_instance.id
            assert info["candidate_version"] == candidate.id
            # exactly ONE replica serves the candidate
            versions = [r.version for r in fleet.replicas]
            assert versions.count(candidate.id) == 1, versions
            # the auto verdict promotes and rolls the rest of the fleet
            _await(lambda: (fleet.canary().get("last") or {}).get(
                "outcome") == "promoted", message="auto-promotion")
            _await(lambda: fleet.version() == candidate.id,
                   message="fleet on the candidate")
        assert not failures, failures[:5]
        assert results.count(200) > 20
        # the verdict that drove the promotion is on the record
        ended = quality.STATE.canary()
        assert ended["outcome"] == "promoted"
        assert ended["verdict"]["verdict"] == "promote"
        assert ended["verdict"]["pairs"] >= 4


def test_degraded_candidate_is_auto_rolled_back(memory_storage,
                                                monkeypatch):
    """Acceptance half 2: chaos latency injected into the canary
    replica blows the serving-latency threshold; the burn-math gate
    fails the candidate and the supervisor swaps the replica BACK onto
    the baseline instance — zero non-429 client errors throughout."""
    monkeypatch.setenv("PIO_CANARY_MIN_PAIRS", "4")
    monkeypatch.setenv("PIO_CANARY_SAMPLE_EVERY", "1")
    monkeypatch.setenv("PIO_DRAIN_TIMEOUT", "5")
    monkeypatch.setenv("PIO_SLO_LATENCY_MS", "100")
    monkeypatch.setenv("PIO_HEDGE_MIN_MS", "50")
    engine, baseline_instance = train_const(memory_storage)
    with canary_fleet(memory_storage, engine) as (fleet, router, base):
        _, candidate = train_const(memory_storage)
        failures, results = [], []
        # the canary pick is the LAST ready replica: degrade it up
        # front (every dispatch takes 300 ms against the 100 ms
        # objective) so not a single clean paired window can sneak a
        # promotion in before the fault is visible
        canary_name = fleet.replicas[-1].name
        chaos.configure(f"batcher@{canary_name}:latency:0.3")
        with _load(base, failures, results):
            status, body, _ = post(
                base + "/admin/fleet",
                body=json.dumps({"canary": "start"}).encode())
            assert status == 202, body
            _await(lambda: fleet.canary().get("active"),
                   message="canary active")
            assert fleet.canary_replica_name() == canary_name
            _await(lambda: (fleet.canary().get("last") or {}).get(
                "outcome") == "rolled_back", message="auto-rollback")
            chaos.clear()
            # the canary replica is restored onto the BASELINE instance
            # and rejoins rotation (the outcome is recorded before the
            # restore swap finishes — wait for the replica itself)
            replica = next(r for r in fleet.replicas
                           if r.name == canary_name)
            _await(lambda: (replica.state == READY
                            and replica.version == baseline_instance.id),
                   message="canary replica restored to baseline")
            assert fleet.version() == baseline_instance.id
        assert not failures, failures[:5]
        assert results.count(200) > 20
        ended = quality.STATE.canary()
        assert ended["outcome"] == "rolled_back"
        assert ended["verdict"]["verdict"] == "rollback"
        # either gate may catch it first: the 300 ms answers fail the
        # burn math, and the overload they cause (canary 429 sheds on
        # paired shadows) fails the quality gate — both are the
        # degradation
        assert ended["verdict"]["reasons"], ended["verdict"]
        # the rejected candidate is remembered so canary-mode watches
        # do not immediately re-canary it
        assert fleet.canary()["last"]["rejected_version"] == candidate.id


def test_quality_surfaces_agree_on_one_source_of_truth(memory_storage,
                                                       monkeypatch,
                                                       capsys):
    """Acceptance: the drift gauges, GET /admin/quality (served by the
    router) and the `pio canary` CLI verdict all render obs/quality.py's
    ONE state — byte-identical numbers, no second bookkeeping."""
    from predictionio_tpu.obs import metrics
    from predictionio_tpu.tools import cli

    monkeypatch.setenv("PIO_CANARY_MIN_PAIRS", "4")
    monkeypatch.setenv("PIO_CANARY_SAMPLE_EVERY", "1")
    monkeypatch.setenv("PIO_CANARY_AUTO", "0")  # hold the canary open
    monkeypatch.setenv("PIO_SLO_LATENCY_MS", "2000")
    engine, _ = train_const(memory_storage)
    with canary_fleet(memory_storage, engine) as (fleet, router, base):
        train_const(memory_storage)
        # a drift report published by the stream lane shows on the same
        # surface the canary uses
        report = quality.publish_drift(
            {"recall_vs_retrain": 0.97, "rmse_drift": 0.02,
             "factor_drift": 0.01, "shadow_instance": "shadow_x",
             "sampled_users": 8})
        status, body, _ = post(
            base + "/admin/fleet",
            body=json.dumps({"canary": "start"}).encode())
        assert status == 202, body
        _await(lambda: fleet.canary().get("active"),
               message="canary active")
        for _ in range(12):
            status, _, _ = post(base + "/queries.json")
            assert status == 200
        _await(lambda: quality.STATE.paired_stats()["n"] >= 4,
               message="paired samples")

        def quiesced():
            # shadow samples ride the worker pool asynchronously: the
            # snapshot-vs-state comparison below needs the accumulator
            # to sit still first
            n = quality.STATE.paired_stats()["n"]
            time.sleep(0.3)
            return quality.STATE.paired_stats()["n"] == n

        _await(quiesced, message="paired sampling quiesced")
        status, served = get_json(base + "/admin/quality")
        assert status == 200
        # gauge == served drift == published report
        assert served["drift"]["recall_vs_retrain"] == 0.97
        assert metrics.REGISTRY.get(
            "pio_model_quality_recall_vs_retrain").value == 0.97
        assert served["drift"] == report
        # the served canary verdict is the verdict the state computes
        direct = quality.STATE.canary_verdict()
        assert served["canary"]["verdict"]["verdict"] == direct["verdict"]
        assert served["canary"]["paired"]["n"] == (
            quality.STATE.paired_stats()["n"])
        # the const engine answers identically and latency is clean:
        # the held-open verdict is promote
        assert direct["verdict"] == "promote"
        # `pio canary` renders the same surface (exit 0: not rollback)
        assert cli.main(["canary", "--url", base]) == 0
        out = capsys.readouterr().out
        assert "PROMOTE" in out
        assert "recall_vs_retrain=0.97" in out
        # explicit operator promote through the CLI's control lane
        assert cli.main(["canary", "--url", base, "--promote"]) == 0
        _await(lambda: not fleet.canary().get("active"),
               message="promotion clears the canary")


def test_canary_admin_contract(memory_storage, monkeypatch):
    """Route-level contract: promote without an active canary answers
    400; double-start answers 409; the snapshot carries the canary
    block."""
    engine, _ = train_const(memory_storage)
    with canary_fleet(memory_storage, engine, n=2) as (fleet, _r, base):
        status, body, _ = post(
            base + "/admin/fleet",
            body=json.dumps({"canary": "promote"}).encode())
        assert status == 400 and "no active canary" in body
        status, body, _ = post(
            base + "/admin/fleet",
            body=json.dumps({"canary": "bogus"}).encode())
        assert status == 400, body
        # no new instance: the start thread records an error verdict
        status, body, _ = post(
            base + "/admin/fleet",
            body=json.dumps({"canary": "start"}).encode())
        assert status == 202, body
        _await(lambda: (fleet.canary().get("last") or {}).get(
            "outcome") == "error", message="no-candidate error")
        assert any("no NEW completed instance" in e for e in
                   fleet.canary()["last"]["errors"])
        status, snap = get_json(base + "/admin/fleet")
        assert status == 200 and "canary" in snap


def test_canary_mode_watch_starts_canary_not_rolling_swap(
        memory_storage, monkeypatch):
    """`pio deploy --canary` semantics: the auto-swap watch lands a new
    COMPLETED instance as a canary, and a rolled-back candidate is not
    auto-retried."""
    monkeypatch.setenv("PIO_FLEET_WATCH_SEC", "0.1")
    monkeypatch.setenv("PIO_CANARY_AUTO", "0")  # decisions by hand here
    engine, baseline_instance = train_const(memory_storage)
    with canary_fleet(memory_storage, engine, n=2,
                      canary_mode=True) as (fleet, _router, base):
        _, candidate = train_const(memory_storage)
        _await(lambda: fleet.canary().get("active"),
               message="watch-started canary")
        assert fleet.canary()["candidate_version"] == candidate.id
        # a rolling reload cannot be started over an active canary
        assert not fleet.start_rolling_reload()
        result = fleet.rollback_canary()
        assert result["action"] == "rollback"
        _await(lambda: fleet.version() == baseline_instance.id,
               message="rollback restored baseline")
        # the watch must NOT re-canary the rejected candidate
        time.sleep(0.5)
        assert not fleet.canary().get("active")
        assert fleet.version() == baseline_instance.id


def test_deploy_canary_needs_a_fleet():
    from predictionio_tpu.tools import cli

    assert cli.main(["deploy", "--canary", "--replicas", "1"]) == 1


# -- review regressions --------------------------------------------------------

def test_rolling_reload_refused_during_canary_deploy_window(
        memory_storage):
    """A rolling swap queued while the canary DEPLOY thread is still
    mid-drain (canary not yet 'active') would silently promote the
    candidate without a verdict — start_rolling_reload must refuse
    while the canary thread lives, symmetric with start_canary's own
    swap-thread check."""
    engine, _ = train_const(memory_storage)
    with canary_fleet(memory_storage, engine, n=2) as (fleet, _r, _b):
        gate = threading.Event()
        deploying = threading.Thread(target=gate.wait, args=(10,))
        deploying.start()
        fleet._canary_thread = deploying
        try:
            assert not fleet.start_rolling_reload()
        finally:
            gate.set()
            deploying.join(timeout=10)
        # thread done and no active canary: swaps work again
        train_const(memory_storage)
        assert fleet.start_rolling_reload()


def test_watch_never_redeploys_rejected_candidate_in_any_mode(
        memory_storage, monkeypatch):
    """After a rollback, the NON-canary-mode watch path must hold the
    rejected instance too — a full rolling swap one watch tick later
    would undo the quality gate's verdict."""
    monkeypatch.setenv("PIO_FLEET_WATCH_SEC", "0.01")
    engine, baseline_instance = train_const(memory_storage)
    with canary_fleet(memory_storage, engine, n=2,
                      canary_mode=False) as (fleet, _r, _b):
        _, rejected = train_const(memory_storage)
        with fleet._state_lock:
            fleet._canary = {"active": False,
                             "last": {"outcome": "rolled_back",
                                      "rejected_version": rejected.id}}
        fleet._last_watch = 0.0
        fleet._maybe_auto_swap()
        time.sleep(0.3)
        assert not fleet.snapshot()["swap"]["active"]
        assert fleet.version() == baseline_instance.id


def test_post_drift_report_registers_on_quality_surface(memory_storage):
    """Split deployments: a stream daemon POSTs its drift probe to the
    fleet's /admin/quality — the fleet's surface then serves it."""
    engine, _ = train_const(memory_storage)
    with canary_fleet(memory_storage, engine, n=2) as (_f, _r, base):
        report = {"recall_vs_retrain": 0.91, "rmse_drift": 0.03,
                  "breached": [], "shadow_instance": "remote_shadow"}
        status, body, _ = post(base + "/admin/quality",
                               body=json.dumps({"drift": report}).encode())
        assert status == 200 and "drift" in body
        status, served = get_json(base + "/admin/quality")
        assert served["drift"] == report
        # a body with neither key is a 400
        status, _, _ = post(base + "/admin/quality",
                            body=json.dumps({"bogus": 1}).encode())
        assert status == 400


def test_stream_pushes_drift_to_patch_targets(memory_storage,
                                              monkeypatch):
    from predictionio_tpu.obs import quality as q

    class _Updater:
        # the push seam in isolation: probe_quality's contract is
        # "publish, then push to patch_urls" — pin that an HTTP-target
        # updater delivers the drift body the route above accepts
        from predictionio_tpu.workflow.stream import StreamUpdater
        _push_drift = StreamUpdater._push_drift

    engine, _ = train_const(memory_storage)
    with canary_fleet(memory_storage, engine, n=2) as (fleet, _r, base):
        updater = _Updater()
        updater.patch_urls = [base]
        updater._push_drift({"recall_vs_retrain": 0.88,
                             "breached": ["recall_vs_retrain"]})
        status, served = get_json(base + "/admin/quality")
        assert served["drift"]["recall_vs_retrain"] == 0.88
