"""FakeWorkflow + upgrade-check + bin-script parity tests (SURVEY §2.3/§2.8)."""

import json
import os
import subprocess
import threading

from predictionio_tpu.parallel.mesh import MeshContext
from predictionio_tpu.workflow.fake import FakeEvalResult, FakeRun, fake_run

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_fake_run_executes_fn_through_eval_plumbing(memory_storage):
    seen = []

    def fn(ctx):
        assert isinstance(ctx, MeshContext)
        seen.append("ran")
        return 42

    assert fake_run(fn, storage=memory_storage) == 42
    assert seen == ["ran"]
    # the run went through the real evaluation workflow: an instance was
    # created and completed, but no_save kept results out of the store
    instances = memory_storage.evaluation_instances().get_all()
    assert len(instances) == 1
    assert instances[0].status == "EVALCOMPLETED"
    assert instances[0].evaluator_results == ""


def test_fake_run_class_api(memory_storage):
    assert FakeRun(lambda ctx: "ok").run(storage=memory_storage) == "ok"


def test_fake_eval_result_no_save():
    r = FakeEvalResult()
    assert r.no_save is True
    assert "FakeEvalResult" in r.to_one_liner()


def test_check_upgrade_noop_without_url(monkeypatch):
    from predictionio_tpu.tools import upgrade

    monkeypatch.delenv("PIO_UPDATE_URL", raising=False)
    upgrade.check_upgrade()  # must not raise or hit the network


def test_check_upgrade_reads_local_server(monkeypatch):
    """Serve {"version": ...} on a local socket; check must not raise."""
    import http.server

    class H(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            body = json.dumps({"version": "99.0.0"}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), H)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        monkeypatch.setenv("PIO_UPDATE_URL", f"http://127.0.0.1:{srv.server_port}/v")
        from predictionio_tpu.tools import upgrade

        upgrade.check_upgrade("test")
    finally:
        srv.shutdown()


def test_bin_scripts_parse():
    for script in ["pio", "pio-start-all", "pio-stop-all", "pio-shell"]:
        path = os.path.join(REPO, "bin", script)
        assert os.access(path, os.X_OK), f"{script} not executable"
        subprocess.run(["bash", "-n", path], check=True)


def test_env_template_covers_repositories():
    with open(os.path.join(REPO, "conf", "pio-env.sh.template")) as f:
        text = f.read()
    for repo in ["METADATA", "EVENTDATA", "MODELDATA"]:
        assert f"PIO_STORAGE_REPOSITORIES_{repo}_NAME" in text
        assert f"PIO_STORAGE_REPOSITORIES_{repo}_SOURCE" in text
